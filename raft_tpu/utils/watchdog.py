"""No-progress watchdog for long device-bound loops on remote backends.

The tunnel backend has a documented half-up failure mode — device
enumeration succeeds, then any compile/execute blocks forever with no
exception to catch (OUTAGE_r05.log 08:27, 15:51 UTC; a wedged train
burned 25 min of a live window before being killed by hand). In-process
there is nothing to interrupt, so the only honest recovery is a daemon
thread that watches a heartbeat and hard-exits the process with a
distinctive code, letting the caller (runbook, driver) log the failure
and re-probe instead of sleeping out its whole timeout budget.

The reference has no analog — local CUDA either works or raises; a
remote-tunnel TPU claim can silently wedge, which makes this a
TPU-deployment subsystem (SURVEY.md §5 failure detection).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

#: process exit code when the watchdog fires (distinct from OOM/crash
#: paths so runbooks can tell "wedged" from "broken")
WEDGED_EXIT_CODE = 3


class HangWatch:
    """Fire ``on_fire`` (default: diagnose + ``os._exit(3)``) if
    :meth:`beat` hasn't been called for ``hang_s`` seconds.

    ``hang_s <= 0`` disables the watchdog entirely: :meth:`start`
    returns None and :meth:`beat` is a no-op stamp. Beats are a single
    monotonic-clock store — safe to call per training-loop iteration.
    """

    def __init__(self, hang_s: float, label: str = "loop",
                 interval: Optional[float] = None,
                 on_fire: Optional[Callable[[float], None]] = None):
        self.hang_s = float(hang_s)
        self.label = label
        if interval is None:
            # check cadence scales with the deadline: production's 30 s
            # poll cost is unchanged, while the tiny deadlines fault
            # drills use (hang_s of a few seconds) fire promptly instead
            # of waiting out a 30 s poll
            interval = (min(30.0, max(0.25, self.hang_s / 4.0))
                        if self.hang_s > 0 else 30.0)
        self.interval = interval
        self._on_fire = on_fire
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._last = time.monotonic()

    def stop(self, timeout: float = 2.0) -> None:
        """Disarm AND reap the watcher (graftthread T5: a thread
        nobody joins is a leak). Cheap: the poll loop's ``Event.wait``
        wakes the moment the stop flag sets, so the join returns in
        milliseconds, not ``interval``. Self-join guarded — an
        ``on_fire`` callback may itself call stop()."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout)

    def _fire(self, stale: float) -> None:
        if self._on_fire is not None:
            self._on_fire(stale)
            return
        print(f"[watchdog] {self.label}: no progress for {stale:.0f}s — "
              "backend wedged (half-up tunnel); exiting "
              f"{WEDGED_EXIT_CODE} so the caller can re-probe",
              file=sys.stderr, flush=True)
        try:
            # postmortem: every thread's stack, so the wedge report says
            # WHERE the loop stuck (compile? device fetch? a lock?)
            # instead of only that it stuck — os._exit gives no
            # traceback and the hung threads can't print their own
            import faulthandler

            faulthandler.dump_traceback(file=sys.stderr)
            sys.stderr.flush()
        except Exception:
            pass  # diagnostics must never block the exit itself
        os._exit(WEDGED_EXIT_CODE)

    def _watch(self) -> None:
        while not self._stop.wait(self.interval):
            stale = time.monotonic() - self._last
            if stale > self.hang_s:
                self._fire(stale)
                return

    def start(self) -> Optional[threading.Thread]:
        if self.hang_s <= 0:
            return None
        self.beat()
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()
        return self._thread
