"""Backend-selection guard for CLIs.

The image's sitecustomize registers the 'axon' remote-TPU PJRT plugin in
every interpreter, and jax initializes it even under ``JAX_PLATFORMS=cpu``
— dialing (and, when the tunnel is down, blocking ~25 min on) the
single-chip relay. When the user explicitly asked for CPU, deregister the
factory BEFORE any backend initialization so CPU runs never touch the
tunnel. Same guard as tests/conftest.py and __graft_entry__.py.
"""

from __future__ import annotations

import os


def respect_cpu_request() -> None:
    """If JAX_PLATFORMS=cpu, make sure the axon plugin can't be dialed."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        return
    try:
        import jax
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - best effort
        pass
