"""Backend-selection guard for CLIs.

The image's sitecustomize registers the 'axon' remote-TPU PJRT plugin in
every interpreter, and jax initializes it even under ``JAX_PLATFORMS=cpu``
— dialing (and, when the tunnel is down, blocking ~25 min on) the
single-chip relay. When the user explicitly asked for CPU, deregister the
factory BEFORE any backend initialization so CPU runs never touch the
tunnel. Same guard as tests/conftest.py and __graft_entry__.py.
"""

from __future__ import annotations

import os
import tempfile


def jax_cache_dir(tag: str) -> str:
    """Per-user persistent-compile-cache dir for ``tag`` (e.g. 'tpu').

    The cache holds trusted serialized executables, so a predictable
    world-writable location would let another local user pre-plant
    entries. Defaults under ``~/.cache``; the directory is created 0700
    and its ownership verified (a guessable name alone is not enough —
    an attacker could pre-create it). Override with RAFT_TPU_CACHE_DIR
    for air-gapped/cluster layouts.
    """
    root = os.environ.get("RAFT_TPU_CACHE_DIR")
    if not root:
        root = os.path.join(
            os.path.expanduser("~/.cache") if os.path.expanduser("~") != "~"
            else tempfile.gettempdir(), "raft_tpu")
    path = os.path.join(root, f"jax_{tag}")
    os.makedirs(path, mode=0o700, exist_ok=True)
    st = os.stat(path)
    if st.st_uid != os.getuid():
        raise RuntimeError(
            f"compile-cache dir {path} is owned by uid {st.st_uid}, not "
            f"{os.getuid()} — refusing to load serialized executables "
            "from it; set RAFT_TPU_CACHE_DIR to a directory you own")
    os.chmod(path, 0o700)
    return path


def enable_persistent_cache(tag: str) -> None:
    """Point jax's compilation cache at :func:`jax_cache_dir` with
    every-entry persistence (the remote-TPU compiles this repo cares
    about are multi-minute; cache everything)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", jax_cache_dir(tag))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def setup_cli(tag: str = "tpu") -> None:
    """Standard CLI preamble: honor JAX_PLATFORMS=cpu (never dial the
    tunnel) and enable the persistent compile cache. One call per
    entrypoint, so a grep for setup_cli audits the sweep."""
    respect_cpu_request()
    enable_persistent_cache(tag)


def respect_cpu_request() -> None:
    """If JAX_PLATFORMS=cpu, make sure the axon plugin can't be dialed."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        return
    try:
        import jax
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - best effort
        pass
