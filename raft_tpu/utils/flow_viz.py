"""Optical-flow visualization: Baker et al. color wheel.

Equivalent of ``/root/reference/core/utils/flow_viz.py`` (itself from
github.com/tomrunia/OpticalFlow_Visualization, MIT). Vectorized over the
channel loop. The fork pins the normalization radius to 3 instead of the
per-frame max (flow_viz.py:128-130) so colors are frame-to-frame consistent
for video output; we keep that behavior behind ``rad_max`` (pass ``None``
for the upstream per-frame normalization), minus the stray debug print.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def make_colorwheel() -> np.ndarray:
    """55-color wheel (Baker et al. ICCV 2007), shape (55, 3)."""
    RY, YG, GC, CB, BM, MR = 15, 6, 4, 11, 13, 6
    ncols = RY + YG + GC + CB + BM + MR
    wheel = np.zeros((ncols, 3))
    col = 0
    wheel[0:RY, 0] = 255
    wheel[0:RY, 1] = np.floor(255 * np.arange(0, RY) / RY)
    col += RY
    wheel[col:col + YG, 0] = 255 - np.floor(255 * np.arange(0, YG) / YG)
    wheel[col:col + YG, 1] = 255
    col += YG
    wheel[col:col + GC, 1] = 255
    wheel[col:col + GC, 2] = np.floor(255 * np.arange(0, GC) / GC)
    col += GC
    wheel[col:col + CB, 1] = 255 - np.floor(255 * np.arange(CB) / CB)
    wheel[col:col + CB, 2] = 255
    col += CB
    wheel[col:col + BM, 2] = 255
    wheel[col:col + BM, 0] = np.floor(255 * np.arange(0, BM) / BM)
    col += BM
    wheel[col:col + MR, 2] = 255 - np.floor(255 * np.arange(MR) / MR)
    wheel[col:col + MR, 0] = 255
    return wheel


def flow_uv_to_colors(u: np.ndarray, v: np.ndarray,
                      convert_to_bgr: bool = False) -> np.ndarray:
    """(H, W) u/v in wheel-normalized units -> (H, W, 3) uint8."""
    wheel = make_colorwheel()
    ncols = wheel.shape[0]

    rad = np.sqrt(u ** 2 + v ** 2)
    a = np.arctan2(-v, -u) / np.pi
    fk = (a + 1) / 2 * (ncols - 1)
    k0 = np.floor(fk).astype(np.int32)
    k1 = np.where(k0 + 1 == ncols, 0, k0 + 1)
    f = (fk - k0)[..., None]

    col0 = wheel[k0] / 255.0
    col1 = wheel[k1] / 255.0
    col = (1 - f) * col0 + f * col1

    in_range = (rad <= 1)[..., None]
    col = np.where(in_range, 1 - rad[..., None] * (1 - col), col * 0.75)

    img = np.floor(255 * col).astype(np.uint8)
    return img[:, :, ::-1] if convert_to_bgr else img


def flow_to_image(flow_uv: np.ndarray, clip_flow: Optional[float] = None,
                  convert_to_bgr: bool = False,
                  rad_max: Optional[float] = 3.0) -> np.ndarray:
    """(H, W, 2) flow -> (H, W, 3) uint8 visualization.

    ``rad_max=3.0`` is the fork's pinned normalization (flow_viz.py:130);
    ``rad_max=None`` restores upstream per-frame max normalization.
    """
    assert flow_uv.ndim == 3 and flow_uv.shape[2] == 2, flow_uv.shape
    if clip_flow is not None:
        flow_uv = np.clip(flow_uv, 0, clip_flow)
    u, v = flow_uv[:, :, 0], flow_uv[:, :, 1]
    if rad_max is None:
        rad_max = np.sqrt(u ** 2 + v ** 2).max()
    eps = 1e-5
    return flow_uv_to_colors(u / (rad_max + eps), v / (rad_max + eps),
                             convert_to_bgr)
