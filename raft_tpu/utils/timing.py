"""Honest timing on the remote 'axon' TPU backend — the ONE place the
scheme lives (BENCH_NOTES.md documents the three wrong schemes that
preceded it; keep them dead).

Hazards this module encodes:

- ``jax.block_until_ready`` returns before execution finishes on the
  remote backend (measured 5× above chip peak) — only a host-side value
  fetch fences.
- Fetching a full-sized output pays D2H over the tunnel at ~100 MB/s,
  dwarfing kernel time — fetch scalars only.
- Timing a loop of separate dispatches measures dispatch; run the loop
  inside ONE executable, chained through a data dependency so XLA cannot
  hoist the loop-invariant body or dead-code-eliminate any output.
- Arrays the timed function only READS (a correlation pyramid, weights)
  must be passed as ``invariants`` — real jit arguments — never Python
  closures: jit embeds closed-over arrays into the HLO as literal
  constants, and on the remote backend a multi-hundred-MB program body
  is rejected by the compile endpoint outright (HTTP 413; observed with
  a 750 MB padded pyramid) and bloats every upload before that limit.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp


def chained_scan(fn: Callable, iters: int) -> Callable:
    """The timed executable: ``iters`` applications of ``fn`` chained
    through an output-derived input nudge, returning one scalar.

    Returns a jitted ``(c, *invariants) -> scalar``; ``fn`` is called as
    ``fn(c, *invariants)``. The invariants ride through the call as jit
    parameters (see module docstring for why closures are forbidden) and
    stay loop-invariant inside the scan — only ``c`` is nudged.

    The nudge consumes EVERY output leaf, so nothing inside ``fn`` — in
    particular a backward pass in a value_and_grad — is dead code, and the
    loop-invariant body cannot be hoisted out of the scan. Exposed
    separately from :func:`chain_timed` so tests can inspect the compiled
    HLO for exactly this property.
    """

    def run(c, *invariants):
        def step(c, _):
            out = fn(c, *invariants)
            probe = sum(jnp.sum(leaf)
                        for leaf in jax.tree_util.tree_leaves(out))
            return c + (probe * 1e-12).astype(c.dtype), ()

        return jnp.ravel(jax.lax.scan(step, c, None, length=iters)[0])[0]

    return jax.jit(run)


def chain_timed(fn: Callable, x0: jax.Array, iters: int,
                *invariants) -> float:
    """Seconds per application of ``fn``, measured inside one executable.

    ``fn(x, *invariants)`` may return any pytree. Returns
    seconds/iteration; one compile+warm call runs first.
    """
    scanned = chained_scan(fn, iters)
    float(scanned(x0, *invariants))     # compile + warm (not timed)
    t0 = time.perf_counter()
    float(scanned(x0, *invariants))     # scalar fetch fences all iterations
    return (time.perf_counter() - t0) / iters


def force_train(state, metrics) -> float:
    """Fence a chained train-step loop: fetch the loss and one param leaf
    of the final state (both transitively depend on every step when state
    is threaded/donated). Returns the loss value."""
    loss = float(jax.device_get(metrics["loss"]))
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    float(jax.device_get(leaf.ravel()[0]))
    return loss
