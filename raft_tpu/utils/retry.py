"""Exponential backoff with jitter — the shared transient-failure policy.

Used by the training supervisor (restart pacing) and model downloads;
anything facing transient failure should route through here instead of
growing its own ad-hoc sleep loop. Deterministic under test: inject
``rng`` and ``sleep``.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type


def backoff_delays(base_s: float = 0.5, max_s: float = 30.0,
                   factor: float = 2.0, jitter: float = 0.5,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Infinite ``base_s * factor**k`` series capped at ``max_s``, each
    term scaled by a uniform draw in ``[1-jitter, 1+jitter]`` — the
    jitter decorrelates restart herds when many supervised jobs die
    together (a preempted pod's worth of trainers must not re-dial the
    backend in lockstep)."""
    if not (0.0 <= jitter <= 1.0):
        raise ValueError(f"jitter={jitter}: must be in [0, 1]")
    rng = random.Random() if rng is None else rng
    delay = min(base_s, max_s)
    while True:
        scale = 1.0 - jitter + 2.0 * jitter * rng.random() if jitter else 1.0
        yield delay * scale
        delay = min(max_s, delay * factor)


def retry(fn: Callable, *, attempts: int = 4, base_s: float = 0.5,
          max_s: float = 30.0, factor: float = 2.0, jitter: float = 0.5,
          retry_on: Tuple[Type[BaseException], ...] = (Exception,),
          on_retry: Optional[Callable[[int, float, BaseException],
                                      None]] = None,
          rng: Optional[random.Random] = None,
          sleep: Optional[Callable[[float], None]] = None):
    """Call ``fn()`` up to ``attempts`` times, sleeping a jittered
    exponential backoff between failures; re-raises the last error.

    ``on_retry(attempt, delay_s, exc)`` is called before each sleep —
    log there so operators see the retries, not silence.
    """
    if attempts < 1:
        raise ValueError(f"attempts={attempts}: must be >= 1")
    if sleep is None:
        sleep = time.sleep  # late-bound: monkeypatchable under test
    delays = backoff_delays(base_s, max_s, factor, jitter, rng)
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as exc:
            if attempt == attempts:
                raise
            delay = next(delays)
            if on_retry is not None:
                on_retry(attempt, delay, exc)
            sleep(delay)
