"""Filesystem-truth scan of an Orbax checkpoint dir's step directories.

Deliberately jax/orbax-free: the supervisor (a tiny parent process that
must outlive backend wedges) and the checkpoint fallback path share one
notion of "which steps exist on disk" that no CheckpointManager's cached
view can go stale on — a child process quarantining a corrupt step or
writing a new one is visible to the next ``listdir``.
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional, Tuple

#: suffix restore_train_state renames torn/corrupt step dirs to; scans
#: (and Orbax's own step parsing) skip anything carrying it
QUARANTINE_SUFFIX = ".corrupt"

# matches Orbax step-dir layouts: "120", "step_120", "checkpoint-120"
_STEP_DIR_RE = re.compile(r"^[A-Za-z_\-]*?(\d+)$")


def quarantine_path(src: str) -> str:
    """First collision-free ``<src>.corrupt[.N]`` destination for
    renaming a damaged artifact aside (step dirs, stage finals) —
    renamed, never deleted, so the bytes stay around for forensics.
    One definition so every quarantine site names things the same way
    and ``step_dirs``' exclusion always matches."""
    dst = src + QUARANTINE_SUFFIX
    i = 0
    while os.path.exists(dst):
        i += 1
        dst = f"{src}{QUARANTINE_SUFFIX}.{i}"
    return dst


def step_dirs(ckpt_dir: str) -> List[Tuple[int, str]]:
    """``(step, dirname)`` for every committed-looking step dir under
    ``ckpt_dir``, newest first; quarantined and in-flight tmp dirs are
    excluded."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    out = []
    for name in names:
        if QUARANTINE_SUFFIX in name or "tmp" in name.lower():
            continue
        m = _STEP_DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(ckpt_dir, name)):
            out.append((int(m.group(1)), name))
    return sorted(out, reverse=True)


def preflight_step(step_path: str) -> Optional[str]:
    """Pure-python integrity probe of one committed step dir: every
    Orbax metadata file (``_CHECKPOINT_METADATA``, ``_METADATA``) must
    exist and parse as JSON. Returns None when the step looks intact,
    else a short reason.

    This runs BEFORE any Orbax/tensorstore reader sees the step —
    deliberately. Handing a torn/corrupt step to the restore machinery
    poisons the process heap even when the failure surfaces as a clean
    Python exception (use-after-free in the async read path; glibc
    "corrupted double-linked list" aborts minutes later in the very
    run that just recovered — reproduced deterministically by the
    fault drills). Metadata is written at commit time, so a crash
    mid-save or zeroed bytes show up here without opening any data
    file. Damage confined to data-file payloads still falls to the
    restore-time exception path."""
    metas = []
    for root, _, files in os.walk(step_path):
        for f in files:
            if f in ("_METADATA", "_CHECKPOINT_METADATA"):
                metas.append(os.path.join(root, f))
    if not metas:
        return "no metadata files (torn or uncommitted save)"
    for p in metas:
        try:
            with open(p, encoding="utf-8") as fh:
                json.load(fh)
        except (OSError, ValueError) as exc:
            return (f"{os.path.relpath(p, step_path)}: "
                    f"{type(exc).__name__}: {exc}")
    return None


def latest_step_on_disk(ckpt_dir: str) -> Optional[int]:
    """Newest on-disk step, or None — the supervisor's restore-point
    probe (two child failures at the same value = deterministic crash)."""
    dirs = step_dirs(ckpt_dir)
    return dirs[0][0] if dirs else None
