"""Host data-pipeline throughput benchmark (VERDICT r1 weak #5).

Measures the full decode -> augment -> collate path of ``PrefetchLoader``
over a synthetic FlyingChairs-shaped dataset written to a temp dir (real
.ppm/.flo files so the file-format readers are on the measured path), and
reports image-pairs/sec. Compare against the TPU step throughput from
``bench.py``: the loader must sustain comfortably more pairs/s than the
accelerator consumes (rule of thumb >= 1.5x) or the input pipeline binds.

The reference's pipeline is torch ``DataLoader(num_workers=4)`` over the
same augmentation math (core/datasets.py:230-231); ours is thread-based
(data/loader.py) — this benchmark is the evidence for whether threads
suffice on the deployment host.

Usage: python -m raft_tpu.cli.loader_bench [--batch 6] [--samples 48]
       [--step-pairs-per-sec N]
"""

from __future__ import annotations

import argparse
import os
import os.path as osp
import tempfile
import time

import numpy as np


def make_synthetic_chairs(root: str, n: int, hw=(384, 512), seed: int = 0):
    """Write n .ppm pairs + .flo files shaped like FlyingChairs frames."""
    from PIL import Image

    from raft_tpu.data import frame_utils

    rng = np.random.RandomState(seed)
    h, w = hw
    os.makedirs(root, exist_ok=True)
    for i in range(n):
        for k in (1, 2):
            img = rng.randint(0, 256, (h, w, 3), np.uint8)
            Image.fromarray(img).save(
                osp.join(root, f"{i:05d}_img{k}.ppm"))
        frame_utils.write_flow(osp.join(root, f"{i:05d}_flow.flo"),
                               rng.randn(h, w, 2).astype(np.float32) * 4)


def build_dataset(root: str, crop=(368, 496)):
    from raft_tpu.data.datasets import FlowDataset

    ds = FlowDataset({"crop_size": crop, "min_scale": -0.1, "max_scale": 1.0,
                      "do_flip": True})  # chairs-stage aug (datasets.py:202)
    n = len(sorted(os.listdir(root))) // 3
    for i in range(n):
        ds.image_list.append([osp.join(root, f"{i:05d}_img1.ppm"),
                              osp.join(root, f"{i:05d}_img2.ppm")])
        ds.flow_list.append(osp.join(root, f"{i:05d}_flow.flo"))
    return ds


def main(argv=None):
    from raft_tpu.data.loader import PrefetchLoader

    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=6)
    p.add_argument("--samples", type=int, default=48)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--no-clamp", action="store_true",
                   help="bypass the host-aware worker clamp to measure "
                        "the contended configuration (how the clamp "
                        "policy itself gets re-validated)")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--step-pairs-per-sec", type=float, default=None,
                   help="measured TPU step throughput to compare against")
    p.add_argument("--wire-dtype", default="uint8",
                   choices=["uint8", "float32"],
                   help="collate wire format; defaults to uint8, what the "
                        "trainer ships (data/loader._collate)")
    args = p.parse_args(argv)

    with tempfile.TemporaryDirectory() as root:
        t0 = time.perf_counter()
        make_synthetic_chairs(root, args.samples)
        print(f"synthesized {args.samples} pairs in "
              f"{time.perf_counter() - t0:.1f}s")

        ds = build_dataset(root)
        loader = PrefetchLoader(ds, args.batch, num_workers=args.workers,
                                clamp=not args.no_clamp,
                                wire_dtype=args.wire_dtype)

        # warm epoch (page cache, thread spin-up), then timed epochs
        for _ in loader:
            pass
        pairs = 0
        t0 = time.perf_counter()
        for _ in range(args.epochs):
            for batch in loader:
                pairs += batch["image1"].shape[0]
        dt = time.perf_counter() - t0
        rate = pairs / dt
        print(f"loader: {pairs} pairs in {dt:.2f}s = {rate:.1f} pairs/s "
              f"(batch {args.batch}, {loader.num_workers} workers "
              f"effective of {args.workers} requested)")
        if args.step_pairs_per_sec:
            ratio = rate / args.step_pairs_per_sec
            verdict = "OK (loader not binding)" if ratio >= 1.5 else \
                "BINDING — input pipeline limits the accelerator"
            print(f"vs step {args.step_pairs_per_sec:.1f} pairs/s: "
                  f"{ratio:.2f}x -> {verdict}")
        return rate


if __name__ == "__main__":
    main()
