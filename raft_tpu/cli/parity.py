"""Backend parity + timing harness — the ``test_trt.py:52-99`` analog.

Runs the same frame pairs through (a) the plain jitted model and (b) the
AOT shape-bucket engine, reports per-pair wall clock for both (with
``block_until_ready`` fences standing in for ``cuda.synchronize``) and the
max flow difference, and optionally writes the stacked side-by-side
visualization video (raft_trt_utils.py:24-51 analog).
"""

from __future__ import annotations

import argparse
import glob
import os
import time

import numpy as np
from PIL import Image

import jax
import jax.numpy as jnp

from raft_tpu.config import ITERS_EXPORT, RAFTConfig
from raft_tpu.ops.padding import InputPadder


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(description="jit vs AOT-engine parity")
    p.add_argument("--model", required=True, help=".pth or .msgpack weights")
    p.add_argument("--path", required=True, help="directory of frames")
    p.add_argument("--small", action="store_true")
    p.add_argument("--iters", type=int, default=ITERS_EXPORT)
    p.add_argument("--video", default=None, help="optional output .avi")
    args = p.parse_args(argv)

    from raft_tpu.serving.engine import RAFTEngine
    from raft_tpu.serving.export import make_serving_fn
    from raft_tpu.training.trainer import load_weights

    cfg = RAFTConfig(small=args.small)
    variables = load_weights(args.model, cfg)
    jit_fn = jax.jit(make_serving_fn(variables, cfg, args.iters))
    engine = RAFTEngine(variables, cfg, iters=args.iters, envelope=[],
                        precompile=False)

    images = sorted(glob.glob(os.path.join(args.path, "*.png"))
                    + glob.glob(os.path.join(args.path, "*.jpg")))
    # decode ALL frames before the loop: host-only PIL work has no
    # reason to interleave with the jit-driven loop (the graftlint R1
    # baseline's hoist candidate — the timed windows themselves never
    # covered it). Kept uint8 until use so a long sequence holds 1/4
    # the float bytes; the per-pair astype below is cheap host work.
    decoded = [np.array(Image.open(f)) for f in images]
    flows = []
    for f1, (raw1, raw2) in zip(images[:-1], zip(decoded[:-1], decoded[1:])):
        im1 = raw1.astype(np.float32)
        im2 = raw2.astype(np.float32)
        # path A: plain jit on the padded shape
        i1 = jnp.asarray(im1)[None]
        i2 = jnp.asarray(im2)[None]
        padder = InputPadder(i1.shape)
        p1, p2 = padder.pad(i1, i2)
        t0 = time.perf_counter()
        # intentional per-frame latency fence — the cuda.synchronize
        # analog this harness exists to measure (test_trt.py:61-66)
        flow_jit = jax.block_until_ready(jit_fn(p1, p2))  # graftlint: disable=R1
        t_jit = time.perf_counter() - t0
        # D2H fetch is part of the reported serving latency, same fence
        flow_jit = np.asarray(padder.unpad(flow_jit)[0])  # graftlint: disable=R1

        # path B: AOT engine (includes its host-side pad/route)
        t0 = time.perf_counter()
        flow_eng = engine.infer_batch(im1[None], im2[None])[0]
        t_eng = time.perf_counter() - t0

        # host math on already-fetched arrays; per-frame by design (the
        # parity report prints one line per pair)
        diff = float(np.abs(flow_jit - flow_eng).max())  # graftlint: disable=R1
        print(f"{os.path.basename(f1)}: jit {t_jit * 1e3:7.1f} ms | "
              f"engine {t_eng * 1e3:7.1f} ms | max|Δflow| {diff:.2e}")
        flows.append(flow_eng)

    if args.video and flows:
        from raft_tpu.serving.video import optical_flow_visualize
        raws = [np.asarray(r, np.uint8) for r in decoded[:-1]]
        out = optical_flow_visualize(flows, args.video, images=raws)
        print(f"wrote {out}")


if __name__ == "__main__":
    main()
