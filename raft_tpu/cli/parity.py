"""Backend parity + timing harness — the ``test_trt.py:52-99`` analog.

Runs the same frame pairs through (a) the plain jitted model and (b) the
AOT shape-bucket engine, reports per-pair wall clock for both (with
``block_until_ready`` fences standing in for ``cuda.synchronize``) and the
max flow difference, and optionally writes the stacked side-by-side
visualization video (raft_trt_utils.py:24-51 analog).
"""

from __future__ import annotations

import argparse
import glob
import os
import time

import numpy as np
from PIL import Image

import jax
import jax.numpy as jnp

from raft_tpu.config import ITERS_EXPORT, RAFTConfig
from raft_tpu.ops.padding import InputPadder


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(description="jit vs AOT-engine parity")
    p.add_argument("--model", required=True, help=".pth or .msgpack weights")
    p.add_argument("--path", required=True, help="directory of frames")
    p.add_argument("--small", action="store_true")
    p.add_argument("--iters", type=int, default=ITERS_EXPORT)
    p.add_argument("--video", default=None, help="optional output .avi")
    args = p.parse_args(argv)

    from raft_tpu.serving.engine import RAFTEngine
    from raft_tpu.serving.export import make_serving_fn
    from raft_tpu.training.trainer import load_weights

    cfg = RAFTConfig(small=args.small)
    variables = load_weights(args.model, cfg)
    jit_fn = jax.jit(make_serving_fn(variables, cfg, args.iters))
    engine = RAFTEngine(variables, cfg, iters=args.iters, envelope=[],
                        precompile=False)

    images = sorted(glob.glob(os.path.join(args.path, "*.png"))
                    + glob.glob(os.path.join(args.path, "*.jpg")))
    flows, raws = [], []
    for f1, f2 in zip(images[:-1], images[1:]):
        im1 = np.array(Image.open(f1)).astype(np.float32)
        im2 = np.array(Image.open(f2)).astype(np.float32)

        # path A: plain jit on the padded shape
        i1 = jnp.asarray(im1)[None]
        i2 = jnp.asarray(im2)[None]
        padder = InputPadder(i1.shape)
        p1, p2 = padder.pad(i1, i2)
        t0 = time.perf_counter()
        flow_jit = jax.block_until_ready(jit_fn(p1, p2))
        t_jit = time.perf_counter() - t0
        flow_jit = np.asarray(padder.unpad(flow_jit)[0])

        # path B: AOT engine (includes its host-side pad/route)
        t0 = time.perf_counter()
        flow_eng = engine.infer_batch(im1[None], im2[None])[0]
        t_eng = time.perf_counter() - t0

        diff = float(np.abs(flow_jit - flow_eng).max())
        print(f"{os.path.basename(f1)}: jit {t_jit * 1e3:7.1f} ms | "
              f"engine {t_eng * 1e3:7.1f} ms | max|Δflow| {diff:.2e}")
        flows.append(flow_eng)
        raws.append(im1.astype(np.uint8))

    if args.video and flows:
        from raft_tpu.serving.video import optical_flow_visualize
        out = optical_flow_visualize(flows, args.video, images=raws)
        print(f"wrote {out}")


if __name__ == "__main__":
    main()
