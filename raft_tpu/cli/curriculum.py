"""Curriculum driver — the ``train_standard.sh`` / ``train_mixed.sh`` analog.

Runs the 4-stage C -> T -> S -> K recipe (train_standard.sh:3-6), each stage
restoring the previous stage's final weights with a fresh LR schedule, which
is exactly how the shell scripts chain ``--restore_ckpt`` (SURVEY.md §6).
"""

from __future__ import annotations

import argparse

from raft_tpu.config import RAFTConfig


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(description="RAFT 4-stage curriculum on TPU")
    p.add_argument("--name", default="raft")
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed", action="store_true",
                   help="train_mixed.sh presets + bf16 compute")
    p.add_argument("--stages", nargs="+",
                   default=["chairs", "things", "sintel", "kitti"])
    p.add_argument("--num_steps", type=int, default=None,
                   help="override steps per stage (smoke runs)")
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--data_root", default="datasets")
    p.add_argument("--checkpoint_dir", default="checkpoints")
    args = p.parse_args(argv)

    from raft_tpu.training.trainer import train_curriculum

    model_cfg = RAFTConfig(small=args.small, mixed_precision=args.mixed)
    overrides = dict(data_root=args.data_root,
                     checkpoint_dir=args.checkpoint_dir)
    if args.num_steps is not None:
        overrides["num_steps"] = args.num_steps
    if args.batch_size is not None:
        overrides["batch_size"] = args.batch_size
    train_curriculum(args.stages, model_cfg, name=args.name,
                     mixed=args.mixed, **overrides)


if __name__ == "__main__":
    main()
