"""Chip/toolchain envelope sanity: matmul peak, HBM BW, dispatch overhead.

Calibrates every other benchmark in this repo against the hardware's
physical limits (v5e-1: ~197 TFLOP/s bf16, ~819 GB/s HBM). If these numbers
are far off, the environment — not the model code — is the story. Timing
uses the loop-inside-one-executable scheme from BENCH_NOTES.md (the remote
axon backend's ``block_until_ready`` returns early, and fetching large
outputs pays tunnel D2H at ~100 MB/s).

Run on the chip:  python -m raft_tpu.cli.envelope
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp


def scan_time(name, body, x0, iters=20, work=None, unit="T/s"):
    """Time ``iters`` chained applications of ``body`` in one executable
    (fencing scheme: raft_tpu/utils/timing.py)."""
    from raft_tpu.utils.timing import chain_timed

    dt = chain_timed(body, x0, iters)
    extra = f"  {work / dt / 1e12:.2f} {unit}" if work else ""
    print(f"{name}: {dt * 1e3:.3f} ms{extra}", flush=True)
    return dt


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args(argv)

    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache_tpu")
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    print(f"backend={jax.default_backend()} devices={jax.devices()}")

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(8192, 8192).astype(np.float32)
                    ).astype(jnp.bfloat16)
    scan_time("matmul 8192^3 bf16 (peak ~197 TFLOP/s)",
              lambda x: (x @ x).astype(jnp.bfloat16), a,
              iters=args.iters, work=2 * 8192**3, unit="TFLOP/s")

    big = jnp.asarray(rng.randn(64, 1024, 1024).astype(np.float32))  # 256 MB
    scan_time("elementwise +1 on 256MB (512MB traffic, peak ~0.8 TB/s)",
              lambda x: x + 1.0, big,
              iters=args.iters, work=512 * 2**20, unit="TB/s")
    scan_time("pad+unpad 256MB by 11px",
              lambda x: jnp.pad(
                  x, ((0, 0), (11, 11), (11, 11)))[:, 11:-11, 11:-11],
              big, iters=args.iters)
    scan_time("tiny op in-scan floor", lambda x: x * 2.0,
              jnp.zeros((8, 128), jnp.float32), iters=100)

    # dispatch overhead: the same tiny op as separate executable launches
    tiny = jnp.zeros((8, 128), jnp.float32)
    tf = jax.jit(lambda x: x * 2.0 + jnp.sum(x) * 1e-12)
    float(jnp.ravel(tf(tiny))[0])
    t0 = time.perf_counter()
    x = tiny
    for _ in range(50):
        x = tf(x)
    float(jnp.ravel(x)[0])
    print(f"per-dispatch overhead (chained separate calls): "
          f"{(time.perf_counter() - t0) / 50 * 1e3:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
