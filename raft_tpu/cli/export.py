"""Export CLI — delegates to :mod:`raft_tpu.serving.export` (test_trt.py
``--gen_onnx`` analog)."""

from raft_tpu.serving.export import main

if __name__ == "__main__":
    main()
