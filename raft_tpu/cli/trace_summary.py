"""Headless XProf trace analysis: top HLO ops + category rollup.

``profile_step --trace-dir`` writes an XPlane trace; the reference's only
"profiler" is manual cuda.synchronize timing (``test_trt.py:74-97``,
SURVEY.md §5), and this environment has no TensorBoard UI — so this tool
turns the trace into the two tables that answer the hotspot question from
a terminal: per-HLO-op self time (with bound_by and measured bandwidth)
and the per-category rollup.

Usage:
    python -m raft_tpu.cli.trace_summary /tmp/raft_trace_onehot --top 25
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from collections import defaultdict


def parse_gviz(d: dict) -> list:
    """gviz table ({cols: [{id}], rows: [{c: [{v}]}]}) -> list of row dicts."""
    cols = [c["id"] for c in d["cols"]]
    rows = [[cell["v"] if isinstance(cell, dict) else cell
             for cell in r["c"]] for r in d.get("rows", [])]
    return [dict(zip(cols, r)) for r in rows]


def _load_hlo_stats(trace_dir: str):
    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise SystemExit(f"no *.xplane.pb under {trace_dir}")
    try:
        # heavy import, keep lazy — and optional: xprof ships with the
        # TPU profiling stack, not with the base env this CLI parses
        # tables in (tests run the report layer without it)
        from xprof.profile_plugin import convert
    except ImportError as exc:
        raise SystemExit(
            "trace_summary needs the XProf trace converter to read "
            f"*.xplane.pb ({exc}).\nInstall it in the capture env: "
            "pip install xprof  (ships with recent tensorboard-"
            "plugin-profile builds),\nor run this tool where "
            "profile_step captured the trace.")

    data = convert.xspace_to_tool_data(paths, "hlo_stats", {})
    out = data[0] if isinstance(data, tuple) else data
    d = json.loads(out if isinstance(out, str) else out.decode())
    return paths, parse_gviz(d)


def _num(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def report(rows: list, top: int) -> None:
    """Print the category rollup + top-N op table for hlo_stats rows."""
    if not rows:
        print("no device op rows — was the trace captured on an "
              "accelerator with device tracing enabled?")
        return

    by_cat = defaultdict(lambda: [0.0, 0])
    total = 0.0
    for r in rows:
        t = _num(r.get("total_self_time"))
        by_cat[r.get("category", "?")][0] += t
        by_cat[r.get("category", "?")][1] += int(_num(r.get("occurrences")))
        total += t
    print(f"\n== self time by HLO category (total {total:,.0f} us) ==")
    for cat, (t, n) in sorted(by_cat.items(), key=lambda kv: -kv[1][0]):
        print(f"{t / max(total, 1e-9) * 100:6.1f}%  {t:12,.0f} us  "
              f"x{n:<7d} {cat}")

    print(f"\n== top {top} ops by self time ==")
    rows = sorted(rows, key=lambda r: -_num(r.get("total_self_time")))
    for r in rows[:top]:
        name = str(r.get("hlo_op_name", "?"))[:48]
        print(f"{_num(r.get('total_self_time_percent')):6.2f}%  "
              f"{_num(r.get('total_self_time')):10,.0f} us  "
              f"x{int(_num(r.get('occurrences'))):<6d} "
              f"{str(r.get('bound_by', '?')):>8s}  "
              f"bw {_num(r.get('measured_memory_bw')):7.1f} GB/s  "
              f"{str(r.get('category', ''))[:18]:18s} {name}")


def main(argv=None):
    p = argparse.ArgumentParser(description="summarize an XProf trace")
    p.add_argument("trace_dir")
    p.add_argument("--top", type=int, default=20)
    args = p.parse_args(argv)

    paths, rows = _load_hlo_stats(args.trace_dir)
    print(f"trace: {len(paths)} xplane file(s), {len(rows)} HLO op rows")
    report(rows, args.top)


if __name__ == "__main__":
    main()
