"""Training CLI — argument surface mirrors the reference ``train.py:217-239``.

Differences: ``--gpus`` is gone (the mesh uses every visible TPU chip; set
``JAX_PLATFORMS``/``XLA_FLAGS`` to shape the device set), ``--resume``
restores the FULL train state (capability upgrade, SURVEY.md §5), and stage
presets fill defaults so single-stage invocations match the shell recipes.
"""

from __future__ import annotations

import argparse
import os
import sys

from raft_tpu.cli._args import add_corr_args, corr_overrides
from raft_tpu.config import RAFTConfig, TrainConfig, stage_config


def build_parser() -> argparse.ArgumentParser:
    # no abbreviations: _supervise strips --supervise/--max_restarts
    # from the child argv by exact name, and an accepted abbreviation
    # (--superv) surviving the strip would re-enter the supervisor in
    # every child — an unbounded process recursion that never trains
    p = argparse.ArgumentParser(description="Train RAFT on TPU",
                                allow_abbrev=False)
    p.add_argument("--name", default="raft", help="name your experiment")
    p.add_argument("--stage", default="chairs",
                   choices=["chairs", "things", "sintel", "kitti"])
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or .msgpack weights to restore")
    p.add_argument("--resume", action="store_true",
                   help="resume full train state from the stage dir")
    p.add_argument("--small", action="store_true")
    p.add_argument("--validation", nargs="+", default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--num_steps", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--image_size", type=int, nargs=2, default=None)
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--mixed_schedule", action="store_true",
                   help="use the train_mixed.sh stage presets")
    p.add_argument("--iters", type=int, default=12)
    p.add_argument("--wdecay", type=float, default=None)
    p.add_argument("--epsilon", type=float, default=1e-8)
    p.add_argument("--clip", type=float, default=1.0)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--gamma", type=float, default=None,
                   help="exponential weighting")
    p.add_argument("--add_noise", action="store_true")
    p.add_argument("--alternate_corr", action="store_true")
    p.add_argument("--fused_loss", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="sequence loss in the upsampler's subpixel domain "
                        "(basic model): identical values, no full-res "
                        "prediction-stack materialization")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--data_root", default="datasets")
    p.add_argument("--checkpoint_dir", default="checkpoints")
    p.add_argument("--log_dir", default="runs")
    p.add_argument("--num_workers", type=int, default=4)
    p.add_argument("--val_freq", type=int, default=None,
                   help="checkpoint + validation cadence in steps")
    p.add_argument("--hang_s", type=float, default=None,
                   help="no-progress watchdog deadline in seconds (exit "
                        "3 on a wedged backend); size it ABOVE first-"
                        "step compile + one sum_freq window + one "
                        "validation pass — see TrainConfig.hang_s")
    p.add_argument("--on_bad_sample", choices=("raise", "skip"), default=None,
                   help="loader policy for a failing decode/augment: "
                        "'skip' resamples with a counted warning instead "
                        "of killing the run (a rotten file is a "
                        "deterministic crash no restart can clear) — "
                        "see TrainConfig.on_bad_sample")
    p.add_argument("--stall_s", type=float, default=None,
                   help="loader batch deadline in seconds: a hung decode "
                        "raises LoaderStallError instead of wedging the "
                        "loop (0 disables) — see TrainConfig.stall_s")
    p.add_argument("--supervise", action="store_true",
                   help="run training as a supervised child process: "
                        "auto-relaunch with --resume after a wedge "
                        "(exit 3), preemption signal, or crash; gives "
                        "up on deterministic failures (two deaths at "
                        "the same restored step) or after "
                        "--max_restarts")
    p.add_argument("--max_restarts", type=int, default=5,
                   help="restart budget under --supervise")
    p.add_argument("--synthetic", type=int, default=None, metavar="N",
                   help="train on N generated chairs-shaped samples instead "
                        "of a real dataset — the full decode→augment→collate "
                        "pipeline still runs (on-chip training evidence when "
                        "datasets can't be staged; the sandbox has no egress)")
    # the measured-best step config (bench ladder: bf16 volumes, onehot)
    # must be reachable from real training runs, not just from bench.py
    add_corr_args(p)
    return p


def configs_from_args(args) -> tuple[RAFTConfig, TrainConfig]:
    model_cfg = RAFTConfig(
        small=args.small, dropout=args.dropout,
        alternate_corr=args.alternate_corr,
        mixed_precision=args.mixed_precision,
        **corr_overrides(args))
    overrides = dict(
        name=args.name, restore_ckpt=args.restore_ckpt, iters=args.iters,
        epsilon=args.epsilon, clip=args.clip, add_noise=args.add_noise,
        seed=args.seed, data_root=args.data_root,
        checkpoint_dir=args.checkpoint_dir, log_dir=args.log_dir,
        num_workers=args.num_workers)
    if args.fused_loss is not None:  # tri-state: None = config auto (fused where available)
        overrides["fused_loss"] = args.fused_loss
    for k in ("lr", "num_steps", "batch_size", "wdecay", "gamma",
              "val_freq", "hang_s", "on_bad_sample", "stall_s"):
        v = getattr(args, k)
        if v is not None:
            overrides[k] = v
    if args.image_size is not None:
        overrides["image_size"] = tuple(args.image_size)
    if args.validation is not None:
        overrides["validation"] = tuple(args.validation)
    train_cfg = stage_config(args.stage, mixed=args.mixed_schedule,
                             **overrides)
    return model_cfg, train_cfg


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    args = build_parser().parse_args(argv)
    if args.supervise:
        sys.exit(_supervise(args, argv))
    from raft_tpu.training.trainer import train

    model_cfg, train_cfg = configs_from_args(args)
    loader = None
    if args.synthetic:
        loader = _synthetic_loader(args.synthetic, train_cfg)
    train(model_cfg, train_cfg, resume=args.resume, loader=loader)


def _strip_flag(argv, flag, nargs):
    out, i = [], 0
    while i < len(argv):
        a = argv[i]
        if a == flag:
            i += 1 + nargs
            continue
        if nargs and a.startswith(flag + "="):
            i += 1
            continue
        out.append(a)
        i += 1
    return out


def _supervise(args, argv) -> int:
    """Relaunch this CLI as a supervised child with ``--resume`` forced
    — the restart path must restore, not retrain (the half of wedge
    recovery the watchdog's exit 3 was waiting for)."""
    from raft_tpu.training.supervisor import Supervisor

    _, train_cfg = configs_from_args(args)
    stage_dir = os.path.join(train_cfg.checkpoint_dir, train_cfg.name,
                             train_cfg.stage)
    child = list(sys.argv[1:]) if argv is None else list(argv)
    child = _strip_flag(child, "--supervise", nargs=0)
    child = _strip_flag(child, "--max_restarts", nargs=1)
    if "--resume" not in child:
        child.append("--resume")
    sup = Supervisor([sys.executable, "-m", "raft_tpu.cli.train", *child],
                     max_restarts=args.max_restarts, ckpt_dir=stage_dir,
                     # restart events land in the SAME metrics.jsonl the
                     # trainer's Logger appends to (trainer.py builds it
                     # under <log_dir>/<name>) — one file, one dashboard
                     # tail for curves and restarts both
                     metrics_path=os.path.join(train_cfg.log_dir,
                                               train_cfg.name,
                                               "metrics.jsonl"))
    return sup.run()


def _synthetic_loader(n: int, train_cfg):
    """Chairs-shaped generated samples through the REAL pipeline.

    The dataset dir persists under ~/.cache so a --resume invocation sees
    the same data; decode, augmentation, and collate are the production
    code paths (loader_bench shares the generator)."""
    import os

    from raft_tpu.cli.loader_bench import build_dataset, make_synthetic_chairs
    from raft_tpu.data.loader import PrefetchLoader

    if n < train_cfg.batch_size:
        raise SystemExit(
            f"--synthetic {n} < batch_size {train_cfg.batch_size}: the "
            f"drop-last loader would yield zero batches and the trainer "
            f"would spin forever — generate at least one batch worth")
    root = os.path.expanduser(f"~/.cache/raft_tpu/synthetic_chairs_{n}")
    marker = os.path.join(root, ".complete")
    if not os.path.exists(marker):
        make_synthetic_chairs(root, n)
        open(marker, "w").close()
    ds = build_dataset(root, crop=train_cfg.image_size)
    return PrefetchLoader(ds, train_cfg.batch_size,
                          num_workers=train_cfg.num_workers,
                          seed=train_cfg.seed, wire_dtype="uint8",
                          on_bad_sample=train_cfg.on_bad_sample,
                          stall_s=train_cfg.stall_s)


if __name__ == "__main__":
    main()
