"""Command-line entry points (the reference's L5 scripts as a package).

The reference's ``train.py``/``evaluate.py``/``demo.py``/``test_trt.py`` all
``sys.path.append('core')`` into an uninstalled tree (train.py:3 etc.); here
each is a proper module:

    python -m raft_tpu.cli.train --name raft-chairs --stage chairs ...
    python -m raft_tpu.cli.evaluate --model ckpt.msgpack --dataset sintel
    python -m raft_tpu.cli.demo --model ckpt.msgpack --path frames/ --out out/
    python -m raft_tpu.cli.export --model ckpt.msgpack --out engine_dir/
    python -m raft_tpu.cli.curriculum --name raft  # train_standard.sh analog
"""
