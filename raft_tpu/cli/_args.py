"""Shared CLI argument groups.

One definition of the per-step RAFTConfig performance knobs — the
correlation-backend pair (corr_impl/corr_dtype) plus the refinement-loop
scan_unroll — for every entry point that builds a model config (demo,
evaluate, train, infer_bench, profile_step) so the flags and their
RAFTConfig plumbing cannot drift apart. Validation of the VALUES lives in
``RAFTConfig.__post_init__`` — the single choke point every caller
(including bench.py's dash-style flags) already goes through.
"""

from __future__ import annotations

import argparse


def add_corr_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--corr_impl", "--corr-impl", default=None,
                   choices=["gather", "onehot", "onehot_t", "softsel", "softsel_t", "pallas"],
                   help="lookup backend override (default: RAFTConfig's)")
    p.add_argument("--corr_dtype", "--corr-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="correlation-pyramid storage dtype; 'bfloat16' "
                        "halves volume traffic (see RAFTConfig.corr_dtype)")
    p.add_argument("--scan_unroll", "--scan-unroll", type=int, default=None,
                   help="refinement-loop lax.scan unroll factor; >1 lets "
                        "XLA pipeline across iteration boundaries (see "
                        "RAFTConfig.scan_unroll)")
    p.add_argument("--gru_impl", "--gru-impl", default=None,
                   choices=["xla", "fused"],
                   help="update-block implementation: 'fused' runs the "
                        "scan-body motion encoder + SepConvGRU lane-major "
                        "with Pallas gate/blend epilogues (see "
                        "RAFTConfig.gru_impl)")


def corr_overrides(args: argparse.Namespace) -> dict:
    """RAFTConfig kwargs for the flags :func:`add_corr_args` added."""
    return {k: v for k, v in (("corr_impl", args.corr_impl),
                              ("corr_dtype", args.corr_dtype),
                              ("scan_unroll", args.scan_unroll),
                              ("gru_impl", args.gru_impl))
            if v is not None}
