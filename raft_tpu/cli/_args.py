"""Shared CLI argument groups.

One definition of the correlation-backend knobs for every entry point
(demo, evaluate, profile_step) so the flags and their RAFTConfig plumbing
cannot drift apart. Validation of the VALUES lives in
``RAFTConfig.__post_init__`` — the single choke point every caller
(including bench.py's dash-style flags) already goes through.
"""

from __future__ import annotations

import argparse


def add_corr_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--corr_impl", "--corr-impl", default=None,
                   choices=["gather", "onehot", "onehot_t", "softsel", "pallas"],
                   help="lookup backend override (default: RAFTConfig's)")
    p.add_argument("--corr_dtype", "--corr-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="correlation-pyramid storage dtype; 'bfloat16' "
                        "halves volume traffic (see RAFTConfig.corr_dtype)")


def corr_overrides(args: argparse.Namespace) -> dict:
    """RAFTConfig kwargs for the flags :func:`add_corr_args` added."""
    return {k: v for k, v in (("corr_impl", args.corr_impl),
                              ("corr_dtype", args.corr_dtype))
            if v is not None}
