"""Profile the jitted train step on the current backend (VERDICT r1 #5).

Runs warmup + N timed steps of the chairs-recipe train step on synthetic
data with per-step ``block_until_ready`` fences, optionally wrapping the
timed window in a ``jax.profiler`` trace, and prints a timing summary plus
the cost-model breakdown from XLA's compiled-module analysis (FLOPs,
bytes accessed, per-device memory) so the hotspot question — corr lookup
vs GRU convs vs input pipeline — is answerable from one command.

On the real chip:   python -m raft_tpu.cli.profile_step --batch 6
On CPU (plumbing):  JAX_PLATFORMS=cpu python -m raft_tpu.cli.profile_step \
                        --batch 1 --hw 64 64 --steps 2
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.cli._args import add_corr_args, corr_overrides


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=6)
    p.add_argument("--hw", type=int, nargs=2, default=[368, 496])
    p.add_argument("--iters", type=int, default=12)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)  # min 1: force() reads
    # the warmup loop's metrics; clamped below
    add_corr_args(p)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat_policy", "--remat-policy", default=None,
                   choices=["full", "dots"],
                   help="remat granularity (with --remat) — lets the "
                        "trace match a remat bench default exactly")
    p.add_argument("--fused_loss", "--fused-loss",
                   action=argparse.BooleanOptionalAction, default=None,
                   help="loss path to trace; default None = the config's "
                        "auto (fused where available), matching what "
                        "default training runs — pass --no-fused-loss to "
                        "trace the reference-exact full-resolution loss")
    p.add_argument("--fp32", action="store_true",
                   help="disable bf16 mixed precision")
    p.add_argument("--trace-dir", default=None,
                   help="write a jax.profiler trace here (view in XProf)")
    args = p.parse_args(argv)
    args.warmup = max(1, args.warmup)
    args.steps = max(1, args.steps)


    from raft_tpu.config import RAFTConfig, stage_config
    from raft_tpu.training.train_step import (create_train_state,
                                              make_train_step)

    overrides = corr_overrides(args)
    if args.remat_policy:
        overrides["remat_policy"] = args.remat_policy
    model_cfg = RAFTConfig(small=False, mixed_precision=not args.fp32,
                           remat=args.remat, **overrides)
    train_cfg = stage_config("chairs", batch_size=args.batch,
                             iters=args.iters,
                             fused_loss=args.fused_loss)

    h, w = args.hw
    rng = jax.random.PRNGKey(0)
    print(f"backend={jax.default_backend()} batch={args.batch} hw={h}x{w} "
          f"iters={args.iters} bf16={not args.fp32} remat={args.remat} "
          f"corr_impl={model_cfg.corr_impl} fused_loss="
          f"{'auto' if args.fused_loss is None else args.fused_loss}")
    t0 = time.perf_counter()
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=(h, w))
    step = jax.jit(make_train_step(model_cfg, train_cfg),
                   donate_argnums=(0,))

    host = np.random.RandomState(0)
    batch = {
        "image1": jnp.asarray(
            host.rand(args.batch, h, w, 3).astype(np.float32) * 255.0),
        "image2": jnp.asarray(
            host.rand(args.batch, h, w, 3).astype(np.float32) * 255.0),
        "flow": jnp.asarray(
            host.randn(args.batch, h, w, 2).astype(np.float32)),
        "valid": jnp.ones((args.batch, h, w), jnp.float32),
    }
    print(f"init: {time.perf_counter() - t0:.1f}s")

    # cost model from the compiled module (works on every backend)
    lowered = step.lower(state, batch, rng)
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        flops = ca.get("flops", float("nan"))
        bytes_acc = ca.get("bytes accessed", float("nan"))
        print(f"cost model: {flops / 1e12:.2f} TFLOP/step, "
              f"{bytes_acc / 2**30:.2f} GiB accessed/step, "
              f"arithmetic intensity {flops / max(bytes_acc, 1):.1f} flop/B")
    except Exception as e:
        print(f"cost_analysis unavailable: {e}")
    try:
        ma = compiled.memory_analysis()
        print(f"memory: temp {ma.temp_size_in_bytes / 2**30:.2f} GiB, "
              f"args {ma.argument_size_in_bytes / 2**30:.2f} GiB "
              f"per device")
    except Exception as e:
        print(f"memory_analysis unavailable: {e}")

    # fencing scheme: raft_tpu/utils/timing.py (block_until_ready lies on
    # the remote backend; time a chained loop, fetch scalars only)
    from raft_tpu.utils.timing import force_train as force

    t0 = time.perf_counter()
    for _ in range(args.warmup):
        state, metrics = step(state, batch, rng)
    loss = force(state, metrics)
    print(f"warmup ({args.warmup} steps incl. compile): "
          f"{time.perf_counter() - t0:.1f}s  loss={loss:.3f}")

    if args.trace_dir:
        jax.profiler.start_trace(args.trace_dir)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step(state, batch, rng)
    loss = force(state, metrics)        # waits for the full chain
    dt = (time.perf_counter() - t0) / args.steps
    if args.trace_dir:
        jax.profiler.stop_trace()
        print(f"trace written to {args.trace_dir}")

    print(f"steps: avg {dt * 1e3:.1f} ms over {args.steps} "
          f"(value-fetch fenced) -> {args.batch / dt:.2f} img-pairs/s")
    try:
        flops = compiled.cost_analysis().get("flops", 0.0)
        print(f"achieved: {flops / dt / 1e12:.2f} TFLOP/s")
    except Exception:
        pass
    return dt


if __name__ == "__main__":
    main()
