"""Synthetic ragged-traffic drill for the serving front-end.

Drives ``MicroBatchScheduler`` + a warm-start ``RAFTEngine`` with
mixed-shape traffic from concurrent submitter threads (plus optional
per-stream video sessions) and prints ONE JSON summary line — the
serving-side counterpart of bench.py's one-line contract, and the
harness the tier-1 acceptance drill (tests/test_scheduler.py) runs at
tiny shapes. The request mix is deliberately ragged: per-shape totals
that don't divide the bucket batch leave a tail the scheduler must
batch-fill into the SAME executables the full micro-batches used.

Run on the real chip (cvt2trt-ish shapes):
    python -m raft_tpu.cli.serve_bench --shapes 440x1024,368x496 \\
        --requests 48 --submitters 2 --bucket-batch 4

``--wire u8`` / ``--pipeline-depth 2`` / ``--device-state`` arm the
zero-copy hot path (uint8 wire, pipelined dispatch, device-resident
session state); the summary line then carries the A/B surface —
``h2d_bytes_per_req``, ``dispatch_gap_{mean,p50,p99}_ms``,
``overlap_ratio`` — against a baseline run of the same traffic.

``--feature-cache`` arms the cross-frame device feature cache
(serving/feature_cache): video sessions serve through the CACHED
bucket signature — steady-state pairs cost ONE encoder pass and ship
ONE frame of H2D — and the summary grows ``warm_pairs_per_s``,
``cache_hit_rate``, ``cache_evictions``. The video-heavy traffic mode
is ``--requests 0 --sessions M --session-frames N`` (long streams, no
one-shot noise); run the SAME line with and without the flag for the
A/B (``serve_cache_r6`` vs its ``_base`` leg in
tools/onchip_round6.sh is that pair at real shapes).

``--ragged`` (+ ``--capacity-classes HxW,...``) serves the SAME mixed
traffic through ONE capacity-class executable instead of one bucket
per distinct HxW: the ragged descriptor
(kernels/corr_ragged_pallas) masks each row to its own extent and the
scheduler coalesces across shapes. The summary grows
``capacity_fill``/``cross_shape_coalesce_rate``/``padding_waste_ratio``
and the A/B against the bucketed baseline is ``executables`` (O(1) vs
O(shapes)) on identical traffic (``serve_ragged_r6`` vs
``serve_bench_r6`` in tools/onchip_round6.sh).

``--chaos N`` instead runs N rounds of randomized fault plans
(raise/hang at ``serve.request`` / ``serve.dispatch_exec`` /
``engine.compile``, seeded probabilities and nth-call scoping) through
the full resilience stack — dispatch watchdog, per-bucket breakers,
engine drop + recompile — and asserts the drill invariants after every
round: every accepted future settled (zero stranded), the accounting
identity submitted == completed + failed + deadline_missed + cancelled,
abandoned_inflight == 0, and health() consistent with the breaker
board. A final fault-free round proves recovery: health back to
healthy and the executable count back at the documented bucket count.

``--models basic,small`` lifts the drill one layer: the traffic drives
a ``ModelRegistry`` (one engine + scheduler + breaker board + metrics
namespace per model), ``--canary F`` deploys a reweighted canary for
the first model at fraction F mid-drill and promotes it after traffic,
and ``--priority-mix I:B`` splits each submitter's requests between
the interactive and batch classes. The JSON line then carries
per-model blocks (latency, occupancy, shed, accounting identity PER
MODEL) and per-priority blocks (latency, shed). With ``--chaos N``
the rounds draw the ``registry.load`` site too: a failed canary
deploy must auto-roll-back and never touch live-model traffic.

``--guardian`` (with ``--models``) hands the rollout verdict to the
SLO guardian (serving/guardian.py): the canary bakes against the live
variant's window metrics under the ``--slo``/``--bake-ms`` policy and
the guardian auto-promotes or auto-rolls-back — the summary grows a
``guardian`` block (decisions with their evidence windows) and the
canary block reports ``resolution=guardian_promote|guardian_rollback|
guardian_undecided``. ``--admission-budget N`` arms the registry-wide
token bucket (``--admission-reserve`` interactive-only tokens);
per-model ``admission_rejected`` counts land in the model blocks.
Under ``--chaos`` the plans additionally draw ``guardian.decide`` — a
guardian that raises or hangs mid-decision must strand nothing and
never leave a half-rolled canary, and the clean round must end in a
guardian auto-promote.

``--trace-path`` (+ ``--trace-sample R``) arms request-scoped tracing
(serving/trace.py): every accepted request's span appends to the
given spans.jsonl (tail exemplars and failures always kept), the
summary line grows a ``tail_exemplars`` block (top-bucket span refs +
the serve_trace phase attribution over them), and under ``--chaos``
the drill additionally pins ZERO orphan spans — every accepted
request closed exactly one span. Read the file back with
``python -m raft_tpu.cli.serve_trace``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import threading
import time
from concurrent.futures import wait as futures_wait


def _ceil8(x: int) -> int:
    return -(-x // 8) * 8


#: the chaos sites the randomized plans draw from — the serving path's
#: three distinct hang/failure surfaces (device call, executor worker,
#: XLA compile)
CHAOS_SITES = ("serve.request", "serve.dispatch_exec", "engine.compile")
#: at pipeline_depth > 1 the blocking fetch moves to the completion
#: stage — its own hang surface, so pipelined chaos draws it too
CHAOS_SITES_PIPELINED = CHAOS_SITES + ("serve.fetch",)
#: registry drills add the model-variant build path: a failed canary
#: deploy must auto-roll-back without touching live traffic
CHAOS_SITES_REGISTRY = CHAOS_SITES + ("registry.load",)
#: guardian-attended drills add the decision point: a guardian that
#: raises or hangs mid-decision must strand nothing and leave routing
#: exactly as it found it (the site fires before any registry mutation)
CHAOS_SITES_GUARDIAN = CHAOS_SITES_REGISTRY + ("guardian.decide",)
#: multi-host drills add the remote lanes' four surfaces: both wire
#: directions (a corrupted/raised exchange must fail over or settle
#: cleanly, never strand), the heartbeat probe (missed beats walk the
#: suspect->dead ladder and the verdict consequences fire), and the
#: worker's infer execution itself (a host dying MID-BATCH — the
#: failover-requeue path, not just the probe path)
CHAOS_SITES_HOSTS = CHAOS_SITES + ("transport.send", "transport.recv",
                                   "host.heartbeat", "host.infer")


def chaos_plan(rng: random.Random, hang_s: float = 0.5,
               sites=CHAOS_SITES) -> dict:
    """One randomized-but-deterministic fault plan: per site, maybe an
    entry with randomized kind (raise/hang), first eligible occurrence
    (``at``), fire budget (``count``) and per-call probability
    (``p``). ``crash`` is deliberately excluded here — an in-process
    drill can't assert anything after ``os._exit``; the crash class is
    drilled via a subprocess (tests/chaos_serve_worker.py) and by the
    PR-3 supervisor layer."""
    faults = []
    for site in sites:
        if rng.random() < 0.25:
            continue  # site spared this round
        faults.append({
            "site": site,
            "kind": "hang" if rng.random() < 0.4 else "raise",
            "at": rng.randint(1, 3),
            "count": rng.randint(1, 3),
            "p": round(rng.uniform(0.3, 0.9), 3),
            "hang_s": hang_s,
        })
    return {"seed": rng.randrange(1 << 16), "faults": faults}


def _trace_file_view(trace_path):
    """The serve_trace read-back over one spans file — the
    whole-file phase attribution + top-bucket membership every drill
    summary assembles the same way."""
    from raft_tpu.cli.serve_trace import (load_spans,
                                          phase_attribution,
                                          top_bucket_membership)
    spans = (load_spans(trace_path)
             if trace_path and os.path.exists(trace_path) else [])
    return {"phase_attribution": phase_attribution(spans),
            "top_bucket": top_bucket_membership(spans)}


def _fresh_trace_file(trace_path):
    """Start a drill's spans file FRESH. The summary reads the whole
    file back, and a new ledger restarts its trace ids at r-1 — a
    reused --trace-path would mix a previous run's spans into this
    run's attribution AND duplicate ids (metrics.jsonl appends by
    convention; spans.jsonl is per-run evidence)."""
    if trace_path and os.path.exists(trace_path):
        os.remove(trace_path)


def _capacity_envelope(shapes, capacity_classes, bucket_batch):
    """The ragged engine's class list: the explicit ``--capacity-classes``
    boxes, or one box covering every drill shape (the O(1)-compile
    default the single-executable gate pins)."""
    if capacity_classes:
        return sorted({(bucket_batch, _ceil8(h), _ceil8(w))
                       for h, w in capacity_classes})
    return [(bucket_batch, max(_ceil8(h) for h, _ in shapes),
             max(_ceil8(w) for _, w in shapes))]


def run_drill(variables, cfg, *, shapes, requests=32, submitters=2,
              bucket_batch=4, iters=2, sessions=0, session_frames=4,
              deadline_s=None, max_queue=64, gather_window_s=0.005,
              dispatch_timeout_s=None, breaker_failures=0,
              breaker_backoff_s=0.25, breaker_backoff_max_s=30.0,
              wire="f32", pipeline_depth=1, session_device_state=False,
              feature_cache=False, cache_capacity=256,
              ragged=False, capacity_classes=None,
              fault_plan=None, recover_s=0.0,
              metrics_path=None, trace_path=None, trace_sample=1.0,
              tracer=None, seed=0, engine=None, aot_cache=None,
              replicas=1, replica_ceiling=None, hosts=0,
              host_kill_one=False):
    """The drill as a library call (tests reuse it, and may pass a
    prebuilt warm-start ``engine`` to share compiles across drills).
    Returns the summary dict the CLI prints.

    ``fault_plan`` arms the fault harness for this drill only (disarmed
    in a finally). ``recover_s`` > 0 runs a post-traffic recovery
    phase: per shape, retry probes until one serves or the budget runs
    out — the half-open probe path that closes an opened breaker and
    lazily recompiles a dropped bucket.

    ``feature_cache=True`` arms the cross-frame device feature cache
    (engine cached signature + scheduler pool of ``cache_capacity``
    slots) and runs every video session through it — the video-warm
    A/B: same traffic with the flag off is the baseline the
    ``warm_pairs_per_s``/``cache_hit_rate`` summary fields compare
    against.

    ``ragged=True`` compiles ONE capacity-class executable
    (``capacity_classes`` boxes, default: one box covering every drill
    shape) instead of one bucket per distinct HxW, and the scheduler
    coalesces ACROSS shapes into it — the A/B against the same traffic
    without the flag compares ``executables`` (O(1) vs O(shapes)),
    ``capacity_fill``, ``cross_shape_coalesce_rate`` and
    ``padding_waste_ratio``.

    ``aot_cache`` (a directory path) arms the serialized-executable
    cache (serving/aot.py): the engine's precompile LOADS any bucket
    whose artifact is already in the dir instead of compiling, and
    stores what it does compile — the load-vs-compile cold-start A/B
    the ``--aot-cache`` rung runs twice against one dir. When armed
    the summary grows ``aot_hits``/``aot_misses``/``compiles``/
    ``compiles_avoided`` (from ``engine.aot_stats()``); off, the
    summary is byte-identical to before.

    ``trace_path`` arms request-scoped tracing (serving/trace.py):
    spans append there under ``trace_sample`` with always-keep-tail
    exemplars, and the summary grows a ``tail_exemplars`` block (the
    top-bucket span refs + the serve_trace phase attribution over
    them + the ledger counters). ``tracer`` injects a prebuilt ledger
    (the chaos harness shares ONE across rounds so trace ids stay
    unique in the shared file). Default off: summary byte-identical
    to the untraced drill.

    ``replicas`` > 1 (or a ``replica_ceiling``) arms the data-parallel
    replica fleet (parallel/placement.py): the engine fans out into N
    lanes warmed from the primary (AOT-loaded when ``aot_cache`` is
    set — zero extra XLA compiles per added lane), micro-batches
    dispatch least-loaded across them, and the summary grows a
    ``fleet`` block with per-replica dispatches/occupancy/breaker
    state/queue depth. At the default ``replicas=1`` the fleet is
    never built and the summary is byte-identical to before.

    ``hosts`` > 0 arms the multi-host fleet (serving/hosts.py): N
    loopback host workers — each a ``HostWorker`` over an engine
    spawned from the primary (AOT-loaded when ``aot_cache`` is set,
    zero extra XLA compiles per host) — behind a ``HostFleet`` with
    heartbeats, breakers and the failover path, admitted (artifact
    push + prewarm) BEFORE any traffic. ``host_kill_one=True`` runs
    the kill-one drill: after every submitter has queued its traffic,
    host ``h0``'s transport is poisoned mid-drain — the missed-beat
    ladder must verdict it dead, its lane quarantine, in-flight
    batches fail over to survivors, and every request still settle
    exactly once. The summary grows a ``hosts`` block (per-host
    state/ready/beats/failovers/rejoins/push counters); at the
    default ``hosts=0`` none of this is built and the summary is
    byte-identical to before."""
    import numpy as np

    from raft_tpu.serving.engine import RAFTEngine
    from raft_tpu.serving.feature_cache import FeatureCacheMiss
    from raft_tpu.serving.resilience import CircuitOpen, DispatchWedged
    from raft_tpu.serving.scheduler import (BackpressureError,
                                            DeadlineExceeded,
                                            MicroBatchScheduler)
    from raft_tpu.serving.session import VideoSession
    from raft_tpu.testing import faults

    if ragged and feature_cache:
        raise ValueError("--ragged with --feature-cache is not "
                         "supported yet (the cached signature keeps "
                         "per-shape buckets)")
    if engine is None:
        if ragged:
            # ONE documented executable per capacity class — the
            # whole mixed-shape drill rides it
            engine = RAFTEngine(
                variables, cfg, iters=iters, precompile=True,
                warm_start=True, wire=wire, ragged=True,
                capacity_classes=_capacity_envelope(
                    shapes, capacity_classes, bucket_batch),
                aot_cache=aot_cache)
        else:
            # one documented bucket per distinct ÷8-padded request shape
            envelope = sorted({(bucket_batch, _ceil8(h), _ceil8(w))
                               for h, w in shapes})
            engine = RAFTEngine(variables, cfg, iters=iters,
                                envelope=envelope, precompile=True,
                                warm_start=True, wire=wire,
                                feature_cache=feature_cache,
                                aot_cache=aot_cache)
    _n_exec = getattr(engine, "executable_count",
                      lambda: len(engine._compiled))
    documented = _n_exec()
    own_ledger = tracer is None and bool(trace_path)
    if own_ledger:
        from raft_tpu.serving.trace import TraceLedger
        _fresh_trace_file(trace_path)
        tracer = TraceLedger(trace_path, sample_rate=trace_sample)
    host_fleet = None
    if hosts:
        from raft_tpu.serving.aot import AOTCache
        from raft_tpu.serving.hosts import HostFleet, HostWorker
        from raft_tpu.serving.transport import LoopbackTransport
        spawn = getattr(engine, "spawn_replica", None)
        if spawn is None:
            raise ValueError("hosts > 0 needs an engine with "
                             "spawn_replica (the host workers wrap "
                             "siblings of the primary)")
        import tempfile
        transports = {}
        for k in range(hosts):
            # each loopback worker gets its OWN artifact root: the
            # admit-time push ships the primary's serialized
            # executables there sha256-verified — the full protocol,
            # even though the in-process sibling warms from the
            # shared store
            root = (tempfile.mkdtemp(prefix=f"raft_host_h{k}_")
                    if aot_cache else None)
            transports[f"h{k}"] = LoopbackTransport(
                HostWorker(spawn(), aot_root=root), name=f"h{k}")
        # short ladder: the kill-one drill must verdict the poisoned
        # host DEAD well inside the drill's drain window; the huge
        # reconnect backoff keeps the monitor from resurrecting the
        # deliberately-killed host mid-assertion
        host_fleet = HostFleet(
            transports,
            aot_cache=AOTCache(aot_cache) if aot_cache else None,
            heartbeat_s=0.05, heartbeat_timeout_s=2.0,
            suspect_after=1, dead_after=2,
            reconnect_backoff_s=600.0, rng=random.Random(seed))
        # admit BEFORE traffic: artifact push + prewarm gate the
        # lanes — zero requests route until every host verified
        host_fleet.admit_all()
    sched = MicroBatchScheduler(engine, max_queue=max_queue,
                                max_batch=bucket_batch,
                                gather_window_s=gather_window_s,
                                dispatch_timeout_s=dispatch_timeout_s,
                                breaker_failures=breaker_failures,
                                breaker_backoff_s=breaker_backoff_s,
                                breaker_backoff_max_s=breaker_backoff_max_s,
                                breaker_rng=random.Random(seed),
                                pipeline_depth=pipeline_depth,
                                feature_cache=feature_cache,
                                feature_cache_capacity=cache_capacity,
                                ragged=ragged,
                                metrics_path=metrics_path,
                                tracer=tracer,
                                replicas=replicas,
                                replica_ceiling=replica_ceiling,
                                host_fleet=host_fleet)
    if feature_cache and sessions:
        # compile-outside-the-measurement discipline (the engine's
        # envelope precompile, one layer up): the device forward-warp
        # jit compiles per 1/8-res shape — warm it here so the first
        # warm pair doesn't pay a one-off compile inside the timed
        # window the A/B compares
        import jax.numpy as jnp

        from raft_tpu.ops.interp import forward_interpolate_device
        for h, w in shapes:
            forward_interpolate_device(
                jnp.zeros((_ceil8(h) // 8, _ceil8(w) // 8, 2))
            ).block_until_ready()
    futures = [[] for _ in range(submitters)]
    shed = [0] * submitters
    rejected = [0] * submitters
    session_stats = {"pairs": 0, "warm": 0, "errors": 0}
    recovery = {"probes": 0, "recovered": 0}

    def submit_loop(sid):
        rng = np.random.RandomState(seed + sid)
        per = requests // submitters + (1 if sid < requests % submitters
                                        else 0)
        for k in range(per):
            h, w = shapes[(sid + k) % len(shapes)]
            i1 = rng.rand(h, w, 3).astype(np.float32) * 255
            i2 = rng.rand(h, w, 3).astype(np.float32) * 255
            try:
                futures[sid].append(
                    sched.submit(i1, i2, deadline_s=deadline_s))
            except BackpressureError:
                shed[sid] += 1
            except CircuitOpen:
                rejected[sid] += 1

    def session_loop(sid):
        rng = np.random.RandomState(seed + 1000 + sid)
        h, w = shapes[sid % len(shapes)]
        sess = VideoSession(sched, deadline_s=deadline_s,
                            device_state=session_device_state,
                            feature_cache=feature_cache)
        futs = []
        for _ in range(session_frames + 1):
            try:
                futs.append(sess.submit_frame(
                    rng.rand(h, w, 3).astype(np.float32) * 255))
            except (BackpressureError, CircuitOpen,
                    FeatureCacheMiss):
                # a FeatureCacheMiss here is a failed re-prime (under
                # injected faults) or sustained capacity churn past
                # the session's bounded re-prime retries — counted
                # like any other lost pair
                session_stats["errors"] += 1
        for f in futs:
            if f is None:
                continue
            try:
                f.result(timeout=600)
                session_stats["pairs"] += 1
            except Exception:
                session_stats["errors"] += 1
        session_stats["warm"] += sess.warm_submits

    def recover_loop():
        """Per shape: probe until one request serves (the breaker's
        half-open round-trip + the dropped bucket's lazy recompile) or
        the budget expires."""
        rng = np.random.RandomState(seed + 5000)
        for h, w in shapes:
            t_end = time.monotonic() + recover_s
            while time.monotonic() < t_end:
                try:
                    fut = sched.submit(
                        rng.rand(h, w, 3).astype(np.float32) * 255,
                        rng.rand(h, w, 3).astype(np.float32) * 255)
                    recovery["probes"] += 1
                    fut.result(timeout=max(recover_s, 30.0))
                    recovery["recovered"] += 1
                    break
                except Exception:
                    time.sleep(0.05)

    threads = ([threading.Thread(target=submit_loop, args=(s,))
                for s in range(submitters)]
               + [threading.Thread(target=session_loop, args=(s,))
                  for s in range(sessions)])
    if fault_plan is not None:
        faults.arm(fault_plan)
    t0 = time.perf_counter()
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if host_kill_one and host_fleet is not None:
            # every submitter has queued its traffic; the queue is
            # still draining — poisoning h0 HERE lands the dead-host
            # verdict mid-traffic (deterministically after admission,
            # deterministically before the drain completes), and the
            # failover path must re-dispatch its in-flight batches
            host_fleet.poison("h0")
        if recover_s > 0:
            recover_loop()
        # settle traffic before reading health: submit threads join as
        # soon as the queue has everything, and a health snapshot taken
        # mid-dispatch would report the PRE-outcome state (a wedge that
        # hasn't happened yet reads healthy — observed on a live drive)
        futures_wait([f for fl in futures for f in fl], timeout=600)
        health = sched.health()         # before close: live liveness
        sched.close(drain=True)         # settles every accepted request
    finally:
        if fault_plan is not None:
            faults.disarm()
    wall = time.perf_counter() - t0

    served = deadline_missed = wedged = circuit = errors = stranded = 0
    for fl in futures:
        for fut in fl:
            if not fut.done():
                stranded += 1   # close(drain=True) settles everything:
                continue        # nonzero == a stranded-future bug
            try:
                fut.result(timeout=0)
                served += 1
            except DeadlineExceeded:
                deadline_missed += 1
            except DispatchWedged:
                wedged += 1
            except CircuitOpen:
                circuit += 1
            except Exception:
                errors += 1
    rec = sched.metrics.snapshot(executables=_n_exec())
    total_served = served + session_stats["pairs"]
    occ = rec["occupancy"]
    rag = rec["ragged"]
    waste = rec["padding_waste"]
    accounted = (rec["completed"] + rec["failed"]
                 + rec["deadline_missed"] + rec["cancelled"])
    open_buckets = sum(1 for b in health["buckets"].values()
                       if b["state"] != "closed")
    hot = rec["hot_path"]
    fc = rec.get("feature_cache") or {}
    summary = {
        "wire": getattr(engine, "wire", "f32"),
        "pipeline_depth": pipeline_depth,
        "submitted": rec["submitted"],
        "accepted": sum(len(fl) for fl in futures),
        "served": served,
        "shed": sum(shed),
        "circuit_rejected": sum(rejected),
        "deadline_missed": deadline_missed,
        "errors": errors + session_stats["errors"],
        "failed_wedged": wedged,
        "failed_circuit": circuit,
        "stranded": stranded,
        "accounting_ok": rec["submitted"] == accounted,
        "abandoned_inflight": rec["abandoned_inflight"],
        "dispatches": rec["dispatches"],
        "executables": _n_exec(),
        "documented_buckets": documented,
        "mean_occupancy": occ["mean"],
        "baseline_occupancy": occ["one_per_dispatch_baseline"],
        # ragged A/B surface: ONE executable per capacity class vs one
        # per shape, box fill, how often a dispatch mixed shapes, and
        # the padding waste both paths report comparably
        "ragged": bool(ragged),
        "capacity_fill": rag["capacity_fill"],
        "cross_shape_coalesce_rate": rag["cross_shape_coalesce_rate"],
        "padding_waste_ratio": waste["waste_ratio"],
        "session_pairs": session_stats["pairs"],
        "warm_submits": session_stats["warm"],
        "recovery_probes": recovery["probes"],
        "recovered_shapes": recovery["recovered"],
        "health_state": health["state"],
        "open_buckets": open_buckets,
        "wedged_dispatches": rec["resilience"]["wedged"],
        "quarantined_threads": rec["resilience"]["quarantined_threads"],
        "breaker_transitions": rec["resilience"]["breaker_transitions"],
        "p50_ms": rec["latency"]["p50_ms"],
        "p99_ms": rec["latency"]["p99_ms"],
        # hot-path A/B surface: wire bytes + dispatch-gap percentiles
        # (the --wire / --pipeline-depth rungs compare THESE lines)
        "h2d_bytes_per_req": hot["h2d_bytes_per_req"],
        "dispatch_gap_mean_ms": hot["dispatch_gap"]["mean_ms"],
        "dispatch_gap_p50_ms": hot["dispatch_gap"]["p50_ms"],
        "dispatch_gap_p99_ms": hot["dispatch_gap"]["p99_ms"],
        "overlap_ratio": hot["assembly"]["overlap_ratio"],
        # video-warm A/B surface (feature cache): warm throughput +
        # the pool's truth about whether streams actually stayed warm
        "feature_cache": bool(feature_cache),
        "warm_pairs_per_s": (round(session_stats["warm"] / wall, 2)
                             if wall else 0.0),
        "cache_hit_rate": fc.get("hit_rate", 0.0),
        "cache_evictions": fc.get("evictions", 0),
        "cache_occupancy": fc.get("occupancy", 0),
        "wall_s": round(wall, 3),
        "pairs_per_s": round(total_served / wall, 2) if wall else 0.0,
    }
    fleet = health.get("fleet")
    if fleet:
        # replica-fleet surface (key absent at replicas=1 — the
        # summary stays byte-identical to the single-engine drill):
        # per-replica dispatch/occupancy/breaker/queue-depth blocks
        # the serve_fleet_r6 rung A/Bs against serve_bench_r6
        reps = rec.get("replicas") or {}
        lanes = {}
        for name, ln in sorted(fleet["lanes"].items()):
            m = reps.get(name[1:], {})
            lanes[name] = {
                "active": ln["active"],
                "quarantined": ln["quarantined"],
                "dispatches": ln["dispatches"],
                "completed": m.get("completed", 0),
                "occupancy": m.get("occupancy", 0.0),
                "queue_depth_last": m.get("queue_depth_last", 0),
                "open_breakers": sum(
                    1 for b in ln["breakers"].values()
                    if b["state"] != "closed"),
            }
        summary["fleet"] = {
            "replicas": fleet["replicas"],
            "active": fleet["active"],
            "ceiling": fleet["ceiling"],
            "concurrency_max": fleet["concurrency_max"],
            "lanes": lanes,
        }
    hf = health.get("hosts")
    if hf:
        # multi-host surface (key absent at hosts=0 — the summary
        # stays byte-identical to the single-process drill): per-host
        # liveness/failover/artifact-push blocks the serve_hosts_r6
        # rung's kill-one drill asserts against
        summary["hosts"] = {
            "state": hf["state"],
            "suspect_after": hf["suspect_after"],
            "dead_after": hf["dead_after"],
            "hosts": {
                name: {
                    "state": blk["state"],
                    "ready": blk["ready"],
                    "beats": blk["beats"],
                    "missed_beats": blk["missed_beats"],
                    "failovers": blk["failovers"],
                    "rejoins": blk["rejoins"],
                    "push_entries": blk["push_entries"],
                    "push_bytes": blk["push_bytes"],
                    "push_retries": blk["push_retries"],
                    "breaker": blk["breaker"]["state"],
                } for name, blk in sorted(hf["hosts"].items())},
        }
    aot = (engine.aot_stats() if hasattr(engine, "aot_stats")
           else {"enabled": 0})
    if aot.get("enabled"):
        # load-vs-compile A/B surface (keys absent with the cache off
        # — the summary stays byte-identical to the uncached drill):
        # the second run against a warm dir must report
        # compiles == 0 and compiles_avoided == the first run's
        # compile count
        summary["aot_hits"] = aot["aot_hits"]
        summary["aot_misses"] = aot["aot_misses"]
        summary["compiles"] = aot["compiles"]
        summary["compiles_avoided"] = aot["compiles_avoided"]
    if tracer is not None:
        # request-tracing surface (key absent when tracing is off —
        # the summary stays byte-identical to the untraced drill):
        # top-bucket span refs from this run's metrics snapshot + its
        # raw accounting counters (the numbers the span classes must
        # reconcile against bucket-for-bucket; recovery probes
        # included — summary["served"] is not)
        summary["tail_exemplars"] = {
            "refs": (rec.get("tail_exemplars") or {}).get("refs", []),
            "accounting": {k: rec[k] for k in
                           ("submitted", "completed", "failed",
                            "deadline_missed", "cancelled")},
        }
        if own_ledger:
            # the ledger and spans file belong to this run alone:
            # counters and the serve_trace read-back are THIS run's.
            # Under a SHARED ledger (chaos rounds) both are
            # cumulative across rounds — the caller owns that view;
            # mixing it into a per-round block would sit cumulative
            # numbers next to per-round counters.
            summary["tail_exemplars"]["ledger"] = tracer.snapshot()
            summary["tail_exemplars"].update(
                _trace_file_view(tracer.path))
    return summary


def _round_violations(s: dict) -> list:
    """The chaos-drill invariants, checked after every round."""
    v = []
    if s["stranded"]:
        v.append(f"stranded futures: {s['stranded']}")
    if not s["accounting_ok"]:
        v.append("submitted != completed+failed+deadline_missed"
                 "+cancelled")
    if s["abandoned_inflight"]:
        v.append(f"abandoned_inflight: {s['abandoned_inflight']}")
    # injected FaultInjected raises land in "errors": settled futures,
    # accounted — not a violation, the drill injected them on purpose
    if s["health_state"] == "healthy" and s["open_buckets"]:
        v.append("health says healthy with open breakers")
    if s["health_state"] == "degraded" and not s["open_buckets"]:
        # a dead/suspect host or a quarantined fleet lane degrades
        # health with every bucket breaker closed — that's the
        # fleet's degradation, not a breaker-accounting bug
        lanes = (s.get("fleet") or {}).get("lanes", {})
        fleet_degraded = (
            s.get("hosts", {}).get("state", "healthy") != "healthy"
            or any(ln["quarantined"] or ln["open_breakers"]
                   for ln in lanes.values()))
        if not fleet_degraded:
            v.append("health says degraded with all breakers closed")
    return v


def run_chaos_drill(variables, cfg, *, shapes, rounds=3, requests=12,
                    submitters=2, bucket_batch=3, iters=1,
                    dispatch_timeout_s=0.4, hang_s=0.8,
                    breaker_failures=2, breaker_backoff_s=0.15,
                    breaker_backoff_max_s=0.6, recover_s=8.0,
                    gather_window_s=0.0, max_queue=64,
                    wire="f32", pipeline_depth=1, sessions=0,
                    session_frames=4, session_device_state=False,
                    feature_cache=False, cache_capacity=256,
                    ragged=False, capacity_classes=None,
                    deadline_s=None, seed=0, metrics_path=None,
                    trace_path=None, trace_sample=1.0, engine=None,
                    aot_cache=None, hosts=0):
    """``rounds`` randomized fault rounds + one clean recovery round
    over ONE shared engine (dropped buckets recompile lazily across
    rounds), asserting the invariants after each. Returns the summary
    dict; ``violations`` is empty iff every invariant held.

    The engine compiles ``exact_shapes=True`` so recovery is honest:
    a dropped bucket must recompile (it can't hide behind a spatially
    larger healthy bucket), pinning the documented executable count
    after the final clean round. With ``ragged=True`` the wedge/drop/
    recompile cycle runs against the capacity-class table instead —
    the chaos passthrough the ragged path must survive unchanged.

    ``trace_path`` arms request tracing across EVERY round through
    ONE shared ledger (trace ids stay unique in the shared file), and
    the chaos invariants grow the span/accounting identity: zero open
    spans after the drill (every accepted request closed exactly one
    span) — the wedge/eviction/deadline outcome tags the test layer
    reconciles bucket-for-bucket.

    ``aot_cache`` arms the serialized-executable cache AND its fault
    site: every chaos round's plan gains an ``aot.load`` corruption
    entry, so when a wedge-dropped bucket recompiles it first hits a
    just-corrupted artifact — the drilled contract is a clean
    miss-and-recompile (the same violations machinery pins it: no
    stranded futures, executables back at the documented count, and a
    corrupted entry is REPLACED on the re-store, proven by the clean
    round loading it again).

    ``hosts`` > 0 runs every round with N loopback host lanes and
    widens the fault vocabulary to ``CHAOS_SITES_HOSTS``: both wire
    directions plus the heartbeat probe — corrupted exchanges must
    settle cleanly (failover or a settled error, never a strand) and
    heartbeat faults walk the missed-beat ladder, firing the verdict
    consequences mid-round. The same invariants pin the outcome."""
    from raft_tpu.serving.engine import RAFTEngine

    if ragged and feature_cache:
        # same boundary as run_drill's: fail before the engine below
        # spends seconds compiling capacity classes
        raise ValueError("ragged=True with feature_cache=True is not "
                         "supported yet (see ROADMAP 'Ragged serving, "
                         "next bricks' (a))")
    rng = random.Random(seed)
    if engine is None:
        if ragged:
            classes = _capacity_envelope(shapes, capacity_classes,
                                         bucket_batch)
            if len(classes) > 1:
                # the recovery pin (executables == documented after
                # the clean round) needs every wedge-dropped class to
                # honestly recompile — with a spatially larger sibling
                # class, dropped-class traffic re-routes there and the
                # drop never restores (the ragged analog of why the
                # bucketed chaos branch forces exact_shapes=True)
                raise ValueError(
                    "--chaos --ragged needs a SINGLE capacity class "
                    "(the default one-covering-box, or one explicit "
                    f"--capacity-classes entry); got {classes}")
            cmax = classes[0]
            bad = [s for s in shapes if _ceil8(s[0]) > cmax[1]
                   or _ceil8(s[1]) > cmax[2]]
            if bad:
                # a shape outside the class would compile-on-miss a
                # new box AFTER the documented-count snapshot, failing
                # the same pin from the other direction
                raise ValueError(
                    f"--chaos --ragged: shapes {bad} exceed the "
                    f"capacity class {cmax}")
            engine = RAFTEngine(
                variables, cfg, iters=iters, precompile=True,
                warm_start=True, wire=wire, ragged=True,
                capacity_classes=classes, aot_cache=aot_cache)
        else:
            envelope = sorted({(bucket_batch, _ceil8(h), _ceil8(w))
                               for h, w in shapes})
            engine = RAFTEngine(variables, cfg, iters=iters,
                                envelope=envelope, precompile=True,
                                warm_start=True, exact_shapes=True,
                                wire=wire, feature_cache=feature_cache,
                                aot_cache=aot_cache)
    _n_exec = getattr(engine, "executable_count",
                      lambda: len(engine._compiled))
    documented = _n_exec()
    tracer = None
    if trace_path:
        from raft_tpu.serving.trace import TraceLedger
        _fresh_trace_file(trace_path)
        tracer = TraceLedger(trace_path, sample_rate=trace_sample)
    per_round = []
    violations = []
    common = dict(shapes=shapes, requests=requests,
                  submitters=submitters, bucket_batch=bucket_batch,
                  iters=iters, deadline_s=deadline_s,
                  max_queue=max_queue, gather_window_s=gather_window_s,
                  dispatch_timeout_s=dispatch_timeout_s,
                  breaker_failures=breaker_failures,
                  breaker_backoff_s=breaker_backoff_s,
                  breaker_backoff_max_s=breaker_backoff_max_s,
                  pipeline_depth=pipeline_depth, sessions=sessions,
                  session_frames=session_frames,
                  session_device_state=session_device_state,
                  feature_cache=feature_cache,
                  cache_capacity=cache_capacity,
                  ragged=ragged, capacity_classes=capacity_classes,
                  recover_s=recover_s, metrics_path=metrics_path,
                  tracer=tracer, engine=engine, hosts=hosts,
                  aot_cache=aot_cache)
    sites = (CHAOS_SITES_PIPELINED if pipeline_depth > 1
             else CHAOS_SITES)
    if hosts:
        sites = sites + ("transport.send", "transport.recv",
                         "host.heartbeat")
    aot_armed = bool(getattr(engine, "aot_stats",
                             lambda: {"enabled": 0})().get("enabled"))
    for r in range(rounds):
        plan = chaos_plan(rng, hang_s=hang_s, sites=sites)
        if aot_armed:
            # cached-artifact bit rot, mid-drill: the first load this
            # round (a wedge-dropped bucket recompiling) reads a
            # just-corrupted entry and must take the clean
            # miss-and-recompile path
            plan["faults"].append({"site": "aot.load", "kind": "corrupt",
                                   "at": 1, "count": 1})
        s = run_drill(variables, cfg, seed=seed + 17 * r,
                      fault_plan=plan, **common)
        s["round"] = r
        s["plan"] = plan
        per_round.append(s)
        violations += [f"round {r}: {v}" for v in _round_violations(s)]
    # the clean round: no faults — recovery must complete (health back
    # to healthy, every shape serving, executables at the documented
    # bucket count with no leaked duplicates from wedged recompiles).
    # The watchdog runs at a production-sized timeout here: the chaos
    # rounds' deliberately short deadline would verdict a legitimate
    # multi-second recompile of a dropped bucket as a wedge (the drill
    # self-heals — the quarantined thread's compile still lands via
    # first-insert-wins — but the round's traffic would fail), and the
    # clean round must prove full recovery, not re-inject noise
    clean = dict(common, dispatch_timeout_s=max(30.0,
                                                dispatch_timeout_s))
    s = run_drill(variables, cfg, seed=seed + 999, fault_plan=None,
                  **clean)
    s["round"] = "clean"
    per_round.append(s)
    violations += [f"clean round: {v}" for v in _round_violations(s)]
    if s["health_state"] != "healthy":
        violations.append(
            f"clean round: health {s['health_state']} != healthy")
    if s["served"] != s["accepted"]:
        violations.append("clean round: served != accepted traffic")
    if _n_exec() != documented:
        violations.append(
            f"executables {_n_exec()} != documented "
            f"{documented} after recovery (leaked/lost bucket)")
    if tracer is not None and tracer.open_count():
        violations.append(
            f"orphan spans: {tracer.open_count()} accepted requests "
            f"never closed a span ({tracer.open_ids()[:8]})")
    if feature_cache:
        # the pool must never leak past its bound — capacity is the
        # memory contract thousands of sessions lean on
        for p in per_round:
            if p["cache_occupancy"] > cache_capacity:
                violations.append(
                    f"round {p['round']}: cache occupancy "
                    f"{p['cache_occupancy']} > capacity "
                    f"{cache_capacity} (leaked slots)")
    totals = {k: sum(p[k] for p in per_round) for k in
              ("submitted", "served", "shed", "circuit_rejected",
               "deadline_missed", "failed_wedged", "failed_circuit",
               "errors", "wedged_dispatches", "quarantined_threads")}
    transitions = {k: sum(p["breaker_transitions"][k] for p in per_round)
                   for k in ("open", "half_open", "closed")}
    out = {
        "chaos_rounds": rounds,
        "violations": violations,
        "documented_buckets": documented,
        "executables": _n_exec(),
        "breaker_transitions": transitions,
        "totals": totals,
        "per_round": per_round,
    }
    if aot_armed:
        out["aot"] = engine.aot_stats()
    if tracer is not None:
        # whole-drill trace view (the per-round blocks carry only
        # their OWN refs/accounting — the shared ledger counters and
        # spans file cover all rounds, so both live here, once)
        out["trace"] = tracer.snapshot()
        out["tail_exemplars"] = _trace_file_view(trace_path)
    return out


def _merged_priority_blocks(variant_snaps):
    """Aggregate per-priority counters + latency across every variant
    snapshot (live + canary + retired finals, all models): counters
    sum, histograms merge on the shared ladder — the per-priority
    summary block of the registry drill's JSON line."""
    from raft_tpu.serving.metrics import LatencyHistogram

    out = {}
    for snap in variant_snaps:
        for cls, p in (snap.get("priority") or {}).items():
            agg = out.setdefault(cls, {
                "submitted": 0, "completed": 0, "shed": 0,
                "deadline_missed": 0, "_hist": LatencyHistogram()})
            for k in ("submitted", "completed", "shed",
                      "deadline_missed"):
                agg[k] += p[k]
            agg["_hist"].merge(
                LatencyHistogram.from_snapshot(p["latency"]))
    for agg in out.values():
        h = agg.pop("_hist")
        agg["p50_ms"] = h.quantile(0.5)
        agg["p99_ms"] = h.quantile(0.99)
        agg["mean_ms"] = (round(h.total / h.count, 3) if h.count
                          else 0.0)
    return out


def _variant_snaps(model_block):
    """Every variant snapshot in one model's registry-snapshot block."""
    snaps = [model_block["live"]]
    if model_block["canary"] is not None:
        snaps.append(model_block["canary"])
    return snaps + list(model_block["retired"])


def run_registry_drill(models, *, shapes, requests=48, submitters=2,
                       bucket_batch=4, iters=2, priority_mix=(1, 1),
                       canary_fraction=0.0, canary_variables=None,
                       promote=True, deadline_s=None, max_queue=64,
                       gather_window_s=0.005, dispatch_timeout_s=None,
                       breaker_failures=0, breaker_backoff_s=0.25,
                       breaker_backoff_max_s=30.0, wire="f32",
                       pipeline_depth=1, sessions=0, session_frames=4,
                       admission_budget=None, admission_reserve=None,
                       guardian=False, guardian_policy=None,
                       guardian_poll_s=0.05, guardian_timeout_s=30.0,
                       fault_plan=None, metrics_path=None,
                       trace_path=None, trace_sample=1.0, seed=0,
                       engines=None, canary_engine=None):
    """Mixed-model, mixed-priority drill over a ``ModelRegistry``.

    ``models``: list of ``(name, variables, config)`` — each becomes a
    live model with its own warm-start engine (one bucket per distinct
    ÷8 request shape), scheduler, breaker board and metrics namespace.
    ``priority_mix``: (interactive, batch) request counts per cycle
    of each submitter's traffic ((0, 0) = priority-less).
    ``canary_fraction`` > 0 deploys ``canary_variables`` as the FIRST
    model's canary before traffic and promotes it after
    (``promote=False`` rolls it back) — under an armed ``fault_plan``
    the deploy may fail, which must auto-roll-back and leave live
    traffic untouched (asserted via the summary's ``canary`` block).
    ``engines``/``canary_engine`` inject prebuilt engines so chaos
    rounds share compiles. Returns the one-line summary dict with
    per-model and per-priority blocks.

    ``guardian=True`` hands the rollout verdict to an
    :class:`~raft_tpu.serving.guardian.SLOGuardian` polling the
    registry (``guardian_policy``: GuardianPolicy kwargs): the drill
    waits up to ``guardian_timeout_s`` for its decision instead of
    promoting/rolling back manually, records it in the summary's
    ``canary``/``guardian`` blocks, and a guardian that never decides
    (wedged at the ``guardian.decide`` chaos site) must leave the
    canary fully routed — never half-rolled — for ``close()`` to
    drain. ``admission_budget`` arms the registry-wide token bucket;
    rejections land per model as ``admission_rejected``."""
    import numpy as np

    from raft_tpu.serving.registry import DeployError, ModelRegistry
    from raft_tpu.serving.resilience import CircuitOpen, DispatchWedged
    from raft_tpu.serving.scheduler import (PRIORITY_BATCH,
                                            PRIORITY_INTERACTIVE,
                                            BackpressureError,
                                            DeadlineExceeded)
    from raft_tpu.serving.session import VideoSession
    from raft_tpu.testing import faults

    envelope = sorted({(bucket_batch, _ceil8(h), _ceil8(w))
                       for h, w in shapes})
    _fresh_trace_file(trace_path)
    reg = ModelRegistry(metrics_path=metrics_path,
                        trace_path=trace_path,
                        trace_sample=trace_sample,
                        max_queue=max_queue,
                        max_batch=bucket_batch,
                        gather_window_s=gather_window_s,
                        dispatch_timeout_s=dispatch_timeout_s,
                        breaker_failures=breaker_failures,
                        breaker_backoff_s=breaker_backoff_s,
                        breaker_backoff_max_s=breaker_backoff_max_s,
                        breaker_rng=random.Random(seed),
                        pipeline_depth=pipeline_depth,
                        admission_budget=admission_budget,
                        admission_interactive_reserve=admission_reserve)
    for name, variables, cfg in models:
        reg.add_model(name, variables, cfg, iters=iters,
                      envelope=envelope,
                      engine=(engines or {}).get(name),
                      warm_start=True, wire=wire)
    guard = None
    if guardian:
        from raft_tpu.serving.guardian import GuardianPolicy, SLOGuardian

        guard = SLOGuardian(reg, GuardianPolicy(**(guardian_policy
                                                   or {})),
                            poll_s=guardian_poll_s).start()
    target = models[0][0]
    canary = {"requested": canary_fraction > 0, "deployed": False,
              "version": None, "deploy_failed": None,
              "leaked_after_failure": False, "resolution": None,
              "half_rolled": False}
    accepted = [[] for _ in range(submitters)]   # (future, model, prio)
    shed = [0] * submitters
    rejected = [0] * submitters
    session_stats = {"pairs": 0, "warm": 0, "errors": 0}
    pi, pb = priority_mix
    cycle = ([PRIORITY_INTERACTIVE] * int(pi)
             + [PRIORITY_BATCH] * int(pb))

    def submit_loop(sid):
        rng = np.random.RandomState(seed + sid)
        per = requests // submitters + (1 if sid < requests % submitters
                                        else 0)
        for k in range(per):
            h, w = shapes[(sid + k) % len(shapes)]
            name = models[(sid * 7 + k) % len(models)][0]
            prio = cycle[k % len(cycle)] if cycle else None
            i1 = rng.rand(h, w, 3).astype(np.float32) * 255
            i2 = rng.rand(h, w, 3).astype(np.float32) * 255
            try:
                accepted[sid].append(
                    (reg.submit(i1, i2, model=name, priority=prio,
                                deadline_s=deadline_s), name, prio))
            except BackpressureError:
                shed[sid] += 1
            except CircuitOpen:
                rejected[sid] += 1

    def session_loop(sid):
        rng = np.random.RandomState(seed + 1000 + sid)
        h, w = shapes[sid % len(shapes)]
        name = models[sid % len(models)][0]
        sess = VideoSession(reg, model=name, deadline_s=deadline_s)
        futs = []
        for _ in range(session_frames + 1):
            try:
                futs.append(sess.submit_frame(
                    rng.rand(h, w, 3).astype(np.float32) * 255))
            except (BackpressureError, CircuitOpen):
                session_stats["errors"] += 1
        for f in futs:
            if f is None:
                continue
            try:
                f.result(timeout=600)
                session_stats["pairs"] += 1
            except Exception:
                session_stats["errors"] += 1
        session_stats["warm"] += sess.warm_submits

    threads = ([threading.Thread(target=submit_loop, args=(s,))
                for s in range(submitters)]
               + [threading.Thread(target=session_loop, args=(s,))
                  for s in range(sessions)])
    if fault_plan is not None:
        faults.arm(fault_plan)
    t0 = time.perf_counter()
    try:
        if canary_fraction > 0:
            # deploy BEFORE traffic: the canary serves its hash slice
            # of the drill. A build failure (incl. the registry.load
            # chaos site) must auto-roll-back: live serves 100% and
            # health shows no canary — the summary pins both.
            try:
                canary["version"] = reg.deploy(
                    target, canary_variables,
                    canary_fraction=canary_fraction,
                    engine=canary_engine)
                canary["deployed"] = True
                if guard is not None:
                    # open the bake BEFORE traffic so the judged
                    # window contains the drill's requests — on a fast
                    # drill the polling loop's first post-deploy tick
                    # could otherwise freeze its baseline after the
                    # traffic already completed
                    guard.tick()
            except DeployError as exc:
                canary["deploy_failed"] = str(exc)[:200]
                canary["leaked_after_failure"] = (
                    reg.health()[target]["canary"] is not None)
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        futures_wait([f for fl in accepted for (f, _, _) in fl],
                     timeout=600)
        if canary["deployed"]:
            if guard is not None:
                # the guardian owns the verdict: wait it out (bounded
                # — a wedged guardian must not wedge the drill), and a
                # canary it never resolved must still be FULLY routed
                # (state canary, fraction > 0) for close() to drain —
                # half-rolled is the invariant violation chaos hunts
                decision = guard.wait_decision(
                    target, timeout=guardian_timeout_s)
                canary["resolution"] = (
                    "guardian_" + decision["action"]
                    if decision is not None else "guardian_undecided")
            elif promote:
                canary["resolution"] = reg.promote(target)["mode"]
            else:
                reg.rollback(target)
                canary["resolution"] = "rolled_back"
        health = reg.health()
        tgt_canary = health[target]["canary"]
        canary["half_rolled"] = (
            tgt_canary is not None
            and (tgt_canary["state"] != "canary"
                 or not tgt_canary["fraction"] > 0))
        guardian_block = None
        if guard is not None:
            guardian_block = {
                "decisions": list(guard.decisions),
                "errors": guard.errors,
                "wedged": not guard.stop(timeout=5.0),
            }
        admission = reg.admission_snapshot()
        reg.close(drain=True)
    finally:
        if guard is not None:
            guard.stop(timeout=0.1)
        if fault_plan is not None:
            faults.disarm()
    wall = time.perf_counter() - t0

    snap = reg.snapshot()    # post-close: retired finals included
    per_model = {}
    for name, _, _ in models:
        blk = snap[name]
        live = blk["live"]
        per_model[name] = {
            "submitted": blk["totals"]["submitted"],
            "completed": blk["totals"]["completed"],
            "failed": blk["totals"]["failed"],
            "shed": blk["totals"]["shed"],
            "evicted": blk["totals"]["evicted"],
            "admission_rejected": blk["totals"]["admission_rejected"],
            "deadline_missed": blk["totals"]["deadline_missed"],
            "cancelled": blk["totals"]["cancelled"],
            "accounting_ok": blk["accounting_ok"],
            "abandoned_inflight": sum(
                s["abandoned_inflight"] for s in _variant_snaps(blk)),
            "occupancy": live["occupancy"]["mean"],
            "p50_ms": live["latency"]["p50_ms"],
            "p99_ms": live["latency"]["p99_ms"],
            "executables_live": live["executables"],
            "health_state": health[name]["live"]["health"]["state"],
        }
    served = deadline_missed = wedged = circuit = errors = 0
    stranded = evicted = 0
    for fl in accepted:
        for fut, _, _ in fl:
            if not fut.done():
                stranded += 1
                continue
            try:
                fut.result(timeout=0)
                served += 1
            except DeadlineExceeded:
                deadline_missed += 1
            except DispatchWedged:
                wedged += 1
            except CircuitOpen:
                circuit += 1
            except BackpressureError:
                # an ACCEPTED future failing backpressure is a
                # shed-batch-first eviction — by design under a
                # priority mix, not a dispatch failure
                evicted += 1
            except Exception:
                errors += 1
    all_snaps = [s for name, _, _ in models
                 for s in _variant_snaps(snap[name])]
    total_served = served + session_stats["pairs"]
    summary = {
        "registry": True,
        "model_names": [name for name, _, _ in models],
        "submitted": sum(b["submitted"] for b in per_model.values()),
        "accepted": sum(len(fl) for fl in accepted),
        "served": served,
        "shed": sum(shed),
        "circuit_rejected": sum(rejected),
        "deadline_missed": deadline_missed,
        "errors": errors + session_stats["errors"],
        "failed_wedged": wedged,
        "failed_circuit": circuit,
        "failed_evicted": evicted,
        "stranded": stranded,
        "accounting_ok": all(b["accounting_ok"]
                             for b in per_model.values()),
        "abandoned_inflight": sum(b["abandoned_inflight"]
                                  for b in per_model.values()),
        "session_pairs": session_stats["pairs"],
        "warm_submits": session_stats["warm"],
        "canary": canary,
        "guardian": guardian_block,
        "admission": admission,
        "models": per_model,
        "priorities": _merged_priority_blocks(all_snaps),
        "wall_s": round(wall, 3),
        "pairs_per_s": round(total_served / wall, 2) if wall else 0.0,
    }
    if reg.tracer is not None:
        # spans carry the model/variant/canary stamps the registry
        # minted — the phase attribution here covers ALL models
        summary["tail_exemplars"] = {
            **_trace_file_view(trace_path),
            "ledger": reg.tracer.snapshot(),
        }
    return summary


def _registry_round_violations(s: dict) -> list:
    """The registry chaos-drill invariants, checked after every
    round: the single-model invariants PER MODEL, plus the canary
    contract — a failed deploy leaves no canary behind and live
    traffic keeps serving."""
    v = []
    if s["stranded"]:
        v.append(f"stranded futures: {s['stranded']}")
    if s["abandoned_inflight"]:
        v.append(f"abandoned_inflight: {s['abandoned_inflight']}")
    for name, blk in s["models"].items():
        if not blk["accounting_ok"]:
            v.append(f"model {name}: submitted != completed+failed+"
                     "deadline_missed+cancelled")
    if (s["canary"]["deploy_failed"] is not None
            and s["canary"]["leaked_after_failure"]):
        v.append("failed canary deploy left a canary routing traffic "
                 "(auto-rollback broken)")
    if s["canary"].get("half_rolled"):
        v.append("canary left half-rolled (present but not fully "
                 "routed) — a wedged guardian must leave routing "
                 "exactly as it found it")
    return v


def run_registry_chaos(models, *, shapes, rounds=2, requests=16,
                       submitters=2, bucket_batch=3, iters=1,
                       priority_mix=(1, 1), canary_fraction=0.5,
                       canary_variables=None, dispatch_timeout_s=0.4,
                       hang_s=0.8, breaker_failures=2,
                       breaker_backoff_s=0.15,
                       breaker_backoff_max_s=0.6,
                       gather_window_s=0.0, max_queue=64,
                       deadline_s=None, guardian=True,
                       guardian_policy=None, guardian_timeout_s=8.0,
                       admission_budget=None, admission_reserve=None,
                       seed=0, metrics_path=None):
    """``rounds`` randomized fault rounds + one clean round of the
    registry drill over SHARED prebuilt engines (compiles amortized
    across rounds; a new registry per round owns fresh schedulers).
    Each round attempts a canary deploy for the first model — the
    plans draw ``registry.load``, so some deploys fail and must
    auto-roll-back without touching live traffic — then runs
    mixed-model mixed-priority traffic and resolves the rollout. With
    ``guardian=True`` (the default) every round runs under a live
    :class:`~raft_tpu.serving.guardian.SLOGuardian` owning the
    verdict, the plans additionally draw the ``guardian.decide`` site
    (a guardian that raises or hangs must strand nothing and never
    leave a half-rolled canary — the violations check pins it), and
    the clean round must end in a guardian auto-promote; with
    ``guardian=False`` rounds resolve manually (promote on even,
    rollback on odd). Either way the clean round needs per-model
    accounting identity, zero stranded futures, and per-engine
    executables back at the documented bucket count."""
    from raft_tpu.serving.engine import RAFTEngine

    rng = random.Random(seed)
    envelope = sorted({(bucket_batch, _ceil8(h), _ceil8(w))
                       for h, w in shapes})
    # exact_shapes, like run_chaos_drill: a wedge-dropped bucket must
    # honestly recompile, not hide behind a larger healthy one
    engines = {name: RAFTEngine(variables, cfg, iters=iters,
                                envelope=envelope, precompile=True,
                                warm_start=True, exact_shapes=True)
               for name, variables, cfg in models}
    canary_engine = RAFTEngine(canary_variables, models[0][2],
                               iters=iters, envelope=envelope,
                               precompile=True, warm_start=True,
                               exact_shapes=True)
    # the recovery check covers every engine population the chaos can
    # wedge — the shared canary engine included, under a reserved key
    all_engines = dict(engines)
    all_engines["_canary"] = canary_engine
    documented = {name: len(eng._compiled)
                  for name, eng in all_engines.items()}
    if guardian:
        # drill-sized bake defaults: judgeable within one round's
        # traffic, margins wide enough that only the drill's own
        # injected faults (not CPU latency jitter) can breach. Caller
        # overrides MERGE on top — a --slo/--bake-ms spec must not
        # silently resurrect the production min_requests=20 against a
        # dozen-request round (seen live: every clean round rolled
        # back insufficient_traffic)
        overrides = guardian_policy or {}
        guardian_policy = {**{"bake_window_s": 0.5, "max_bake_s": 6.0,
                              "min_requests": 1, "p99_ratio": 4.0,
                              "p99_slack_ms": 500.0,
                              "err_rate_margin": 0.3, "max_wedged": 1,
                              "max_breaker_opens": 2},
                           **overrides}
        if "max_bake_s" not in overrides:
            # a caller-sized bake window (--bake-ms) must not collide
            # with the drill default ceiling (GuardianPolicy rejects
            # max_bake_s < bake_window_s)
            guardian_policy["max_bake_s"] = max(
                guardian_policy["max_bake_s"],
                4.0 * guardian_policy["bake_window_s"])
    common = dict(shapes=shapes, requests=requests,
                  submitters=submitters, bucket_batch=bucket_batch,
                  iters=iters, priority_mix=priority_mix,
                  canary_fraction=canary_fraction,
                  canary_variables=canary_variables,
                  deadline_s=deadline_s, max_queue=max_queue,
                  gather_window_s=gather_window_s,
                  dispatch_timeout_s=dispatch_timeout_s,
                  breaker_failures=breaker_failures,
                  breaker_backoff_s=breaker_backoff_s,
                  breaker_backoff_max_s=breaker_backoff_max_s,
                  guardian=guardian, guardian_policy=guardian_policy,
                  guardian_timeout_s=guardian_timeout_s,
                  admission_budget=admission_budget,
                  admission_reserve=admission_reserve,
                  metrics_path=metrics_path, engines=engines,
                  canary_engine=canary_engine)
    per_round = []
    violations = []
    sites = (CHAOS_SITES_GUARDIAN if guardian
             else CHAOS_SITES_REGISTRY)
    for r in range(rounds):
        plan = chaos_plan(rng, hang_s=hang_s, sites=sites)
        if r == 0:
            # every chaos run proves the auto-rollback contract at
            # least once: round 0's deploy is FORCED to fail at
            # registry.load (the randomized entries may or may not
            # draw the site) — the round then runs live-only and the
            # violations check pins no-canary-leaked + accounting
            plan["faults"] = [f for f in plan["faults"]
                              if f["site"] != "registry.load"]
            plan["faults"].append({"site": "registry.load",
                                   "kind": "raise", "at": 1,
                                   "count": 1})
        s = run_registry_drill(models, seed=seed + 17 * r,
                               fault_plan=plan, promote=(r % 2 == 0),
                               **common)
        s["round"] = r
        s["plan"] = plan
        per_round.append(s)
        violations += [f"round {r}: {v}"
                       for v in _registry_round_violations(s)]
    # clean round at a production-sized watchdog (same reasoning as
    # run_chaos_drill: a legitimate recompile of a chaos-dropped
    # bucket must not verdict as a wedge mid-recovery)
    clean = dict(common, dispatch_timeout_s=max(30.0,
                                                dispatch_timeout_s))
    s = run_registry_drill(models, seed=seed + 999, fault_plan=None,
                           promote=True, **clean)
    s["round"] = "clean"
    per_round.append(s)
    violations += [f"clean round: {v}"
                   for v in _registry_round_violations(s)]
    if not s["canary"]["deployed"] or s["canary"]["resolution"] is None:
        violations.append("clean round: canary deploy/promote did not "
                          "complete")
    elif guardian and s["canary"]["resolution"] != "guardian_promote":
        # the clean round's canary bakes with zero injected faults: the
        # guardian must judge it clean and auto-promote — anything else
        # (rollback, undecided) is a broken judgment loop
        violations.append(
            "clean round: guardian resolution "
            f"{s['canary']['resolution']!r} != guardian_promote")
    if s["served"] != s["accepted"]:
        violations.append("clean round: served != accepted traffic")
    for name, eng in all_engines.items():
        if len(eng._compiled) != documented[name]:
            violations.append(
                f"model {name}: executables {len(eng._compiled)} != "
                f"documented {documented[name]} after recovery")
    totals = {k: sum(p[k] for p in per_round) for k in
              ("submitted", "served", "shed", "circuit_rejected",
               "deadline_missed", "failed_wedged", "failed_circuit",
               "errors")}
    deploys = {"attempted": sum(1 for p in per_round
                                if p["canary"]["requested"]),
               "deployed": sum(1 for p in per_round
                               if p["canary"]["deployed"]),
               "auto_rolled_back": sum(
                   1 for p in per_round
                   if p["canary"]["deploy_failed"] is not None)}
    guardian_summary = None
    if guardian:
        guardian_summary = {
            "decisions": sum(len(p["guardian"]["decisions"])
                             for p in per_round if p["guardian"]),
            "errors": sum(p["guardian"]["errors"]
                          for p in per_round if p["guardian"]),
            "wedged_rounds": sum(1 for p in per_round
                                 if p["guardian"]
                                 and p["guardian"]["wedged"]),
            "undecided_rounds": sum(
                1 for p in per_round
                if p["canary"]["resolution"] == "guardian_undecided"),
        }
    return {
        "chaos_rounds": rounds,
        "registry": True,
        "violations": violations,
        "documented_buckets": documented,
        "executables": {name: len(eng._compiled)
                        for name, eng in all_engines.items()},
        "deploys": deploys,
        "guardian": guardian_summary,
        "totals": totals,
        "per_round": per_round,
    }


#: --slo spec keys → GuardianPolicy kwargs (floats unless noted)
_SLO_KEYS = {"p99_ms": "p99_ceiling_ms", "p99_ratio": "p99_ratio",
             "p99_slack_ms": "p99_slack_ms",
             "err_rate": "err_rate_margin",
             "min_requests": "min_requests", "wedged": "max_wedged",
             "breaker_opens": "max_breaker_opens"}
_SLO_INT_KEYS = ("min_requests", "wedged", "breaker_opens")


def _parse_slo(spec: str) -> dict:
    """``--slo`` spec → GuardianPolicy kwargs: a comma list of
    ``key:value`` pairs, e.g. ``p99_ms:500,err_rate:0.05`` (absolute
    canary p99 ceiling + error-rate margin over live) or
    ``p99_ratio:2.0,wedged:0``. Unknown keys exit with usage — a typo
    must not silently run an unguarded bake."""
    out = {}
    for part in spec.split(","):
        if not part:
            continue
        key, sep, val = part.partition(":")
        dest = _SLO_KEYS.get(key.strip())
        if not sep or dest is None:
            raise SystemExit(
                f"--slo {spec!r}: expected comma-separated key:value "
                f"pairs with keys from {sorted(_SLO_KEYS)} "
                "(e.g. p99_ms:500,err_rate:0.05)")
        try:
            out[dest] = (int(val) if key.strip() in _SLO_INT_KEYS
                         else float(val))
        except ValueError:
            raise SystemExit(
                f"--slo {spec!r}: {key.strip()!r} needs a number, "
                f"got {val!r}")
    return out


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(
        description="serving front-end ragged-traffic drill")
    p.add_argument("--shapes", default="64x64,48x48",
                   help="comma list of HxW request shapes (the mixed "
                        "traffic); one bucket per distinct ÷8 shape")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--submitters", type=int, default=2)
    p.add_argument("--bucket-batch", type=int, default=4,
                   help="bucket batch dim = coalescing ceiling")
    p.add_argument("--sessions", type=int, default=0,
                   help="concurrent warm-start video sessions")
    p.add_argument("--session-frames", type=int, default=4)
    p.add_argument("--deadline-ms", type=float, default=0,
                   help="per-request deadline (0: none)")
    p.add_argument("--queue", type=int, default=64)
    p.add_argument("--gather-ms", type=float, default=5.0)
    p.add_argument("--iters", type=int, default=20,
                   help="refinement iterations (export bakes 20)")
    p.add_argument("--small", action="store_true")
    p.add_argument("--chaos", type=int, default=0, metavar="N",
                   help="run N randomized fault rounds + a clean "
                        "recovery round through the resilience stack "
                        "and assert the drill invariants (exit 1 on "
                        "any violation)")
    p.add_argument("--dispatch-timeout-ms", type=float, default=0,
                   help="dispatch watchdog deadline (0: off; --chaos "
                        "default 400ms)")
    p.add_argument("--breaker-failures", type=int, default=0,
                   help="consecutive failures opening a bucket's "
                        "breaker (0: off; --chaos default 2)")
    p.add_argument("--breaker-backoff-ms", type=float, default=250.0)
    p.add_argument("--breaker-backoff-max-ms", type=float,
                   default=30000.0,
                   help="backoff ceiling; size it ABOVE a real "
                        "recompile or half-open probes churn against "
                        "a bucket that can't come back yet")
    p.add_argument("--hang-ms", type=float, default=800.0,
                   help="injected hang length for --chaos plans (must "
                        "exceed the dispatch timeout to wedge)")
    p.add_argument("--recover-s", type=float, default=0.0,
                   help="per-shape recovery-probe budget after "
                        "traffic (drives the half-open probe; --chaos "
                        "default 8s)")
    p.add_argument("--wire", choices=("f32", "u8"), default="f32",
                   help="host→device wire format: u8 ships uint8 "
                        "frames and normalizes on device (~4x fewer "
                        "H2D bytes; bitwise at integer inputs)")
    p.add_argument("--pipeline-depth", type=int, default=1,
                   help="dispatch pipeline stages: 2 assembles+ships "
                        "batch N+1 while the device computes batch N "
                        "and moves the blocking fetch to a completion "
                        "stage (1: historical synchronous path)")
    p.add_argument("--device-state", action="store_true",
                   help="video sessions keep flow_low on device "
                        "between pairs (on-device forward warp) "
                        "instead of the per-frame D2H→H2D round trip")
    p.add_argument("--feature-cache", action="store_true",
                   help="arm the cross-frame device feature cache: "
                        "video sessions serve through the cached "
                        "bucket signature — one encoder pass and ONE "
                        "frame of H2D per steady-state pair; summary "
                        "grows warm_pairs_per_s / cache_hit_rate / "
                        "cache_evictions (A/B against the same "
                        "traffic without the flag)")
    p.add_argument("--cache-capacity", type=int, default=256,
                   help="feature-cache pool slots (LRU beyond; the "
                        "per-stream device-memory bound)")
    p.add_argument("--ragged", action="store_true",
                   help="serve every shape through ONE capacity-class "
                        "executable (ragged descriptor, masked-tail "
                        "correlation) and coalesce micro-batches "
                        "ACROSS shapes; the summary's capacity_fill / "
                        "cross_shape_coalesce_rate / executables are "
                        "the A/B against the same traffic without the "
                        "flag")
    p.add_argument("--capacity-classes", default=None, metavar="HxW,...",
                   help="with --ragged: explicit capacity-class boxes "
                        "(each compiled at --bucket-batch rows); "
                        "default is one box covering every --shapes "
                        "entry")
    p.add_argument("--models", default=None,
                   help="comma list of arch names (basic|small) to "
                        "serve as independent live models behind a "
                        "ModelRegistry (one engine/scheduler/metrics "
                        "namespace per model); the summary line gains "
                        "per-model and per-priority blocks")
    p.add_argument("--canary", type=float, default=0.0, metavar="F",
                   help="with --models: deploy a reweighted canary "
                        "for the FIRST model at this deterministic "
                        "request-hash fraction before traffic, and "
                        "promote it after (same-arch: executables "
                        "reused via update_weights)")
    p.add_argument("--priority-mix", default="0:0", metavar="I:B",
                   help="with --models: interactive:batch request "
                        "counts per cycle of each submitter's "
                        "traffic (0:0 = priority-less)")
    p.add_argument("--guardian", action="store_true",
                   help="with --models: an SLOGuardian owns the "
                        "rollout verdict — it bakes the canary "
                        "against the live variant's window metrics "
                        "and auto-promotes (clean) or auto-rolls-back "
                        "(SLO breach); the summary gains a guardian "
                        "block (decisions + evidence). With --chaos "
                        "the plans also draw guardian.decide")
    p.add_argument("--slo", default=None, metavar="K:V,...",
                   help="guardian SLO margins as key:value pairs "
                        "(keys: p99_ms absolute canary p99 ceiling, "
                        "p99_ratio/p99_slack_ms vs live, err_rate "
                        "margin over live, min_requests, wedged, "
                        "breaker_opens), e.g. p99_ms:500,err_rate:0.05")
    p.add_argument("--bake-ms", type=float, default=2000.0,
                   help="guardian bake window before a clean canary "
                        "auto-promotes (max bake = 4x)")
    p.add_argument("--admission-budget", type=int, default=0,
                   metavar="N",
                   help="with --models: registry-wide admission "
                        "budget — at most N admitted-but-unsettled "
                        "requests across ALL models; exhaustion fails "
                        "fast with BackpressureError, counted per "
                        "model as admission_rejected (0: off)")
    p.add_argument("--admission-reserve", type=int, default=None,
                   metavar="R",
                   help="interactive-only slice of the admission "
                        "budget (default N/4): batch-class traffic "
                        "can never take the last R tokens")
    p.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="data-parallel replica fleet: fan the engine "
                        "out into N lanes behind one scheduler "
                        "(parallel/placement.py); replicas 2..N warm "
                        "from --aot-cache when set (zero extra XLA "
                        "compiles per lane) and the summary grows a "
                        "per-replica 'fleet' block. Default 1: no "
                        "fleet, byte-identical summary")
    p.add_argument("--replica-ceiling", type=int, default=None,
                   metavar="M",
                   help="autoscale bound: queue pressure may grow the "
                        "fleet up to M lanes and idle lanes retire "
                        "back toward the --replicas floor")
    p.add_argument("--hosts", type=int, default=0, metavar="N",
                   help="multi-host fleet (serving/hosts.py): N "
                        "loopback host workers behind the transport "
                        "seam, admitted via artifact push + prewarm "
                        "before traffic, heartbeat-monitored with "
                        "failover; the summary grows a per-host "
                        "'hosts' block. With --chaos the plans also "
                        "draw transport.send / transport.recv / "
                        "host.heartbeat. Default 0: no fleet, "
                        "byte-identical summary")
    p.add_argument("--hosts-kill-one", action="store_true",
                   help="with --hosts: poison host h0 after "
                        "submission while the queue drains — the "
                        "kill-one drill (dead verdict, lane "
                        "quarantine, failover to survivors, every "
                        "request settled exactly once)")
    p.add_argument("--aot-cache", default=None, metavar="DIR",
                   help="serialized-executable cache dir "
                        "(serving/aot.py): precompile LOADS artifacts "
                        "already there instead of compiling, stores "
                        "what it compiles; the summary grows aot_hits/"
                        "aot_misses/compiles/compiles_avoided. Run the "
                        "same drill twice against one dir for the "
                        "load-vs-compile cold-start A/B")
    p.add_argument("--log-dir", default=None,
                   help="append the metrics snapshot to "
                        "<log-dir>/metrics.jsonl")
    p.add_argument("--trace-path", default=None, metavar="PATH",
                   help="arm request-scoped tracing: write span "
                        "records (serving/trace.py) here — the file "
                        "is started FRESH each run (per-run trace "
                        "ids; move it aside to keep old evidence); "
                        "the summary grows a tail_exemplars block and "
                        "raft_tpu.cli.serve_trace reads the file back")
    p.add_argument("--trace-sample", type=float, default=None,
                   metavar="R",
                   help="span sampling rate in [0,1] (default 1.0 "
                        "when tracing is armed); tail exemplars and "
                        "failures are always kept. Without "
                        "--trace-path, spans land beside the metrics "
                        "at <log-dir>/spans.jsonl")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT

    shapes = [tuple(int(v) for v in s.split("x"))
              for s in args.shapes.split(",")]
    capacity_classes = None
    if args.capacity_classes:
        capacity_classes = [tuple(int(v) for v in s.split("x"))
                            for s in args.capacity_classes.split(",")]
    if capacity_classes and not args.ragged:
        raise SystemExit("--capacity-classes needs --ragged")
    if args.ragged and args.feature_cache:
        # validated HERE, not after model init + engine compiles: the
        # chaos path used to build (and compile) its ragged engine
        # first and only then trip run_drill's check as a raw
        # traceback — seconds of work for an unactionable error
        raise SystemExit(
            "--ragged with --feature-cache is not supported yet: the "
            "cached signature keeps its per-shape bucket table. See "
            "ROADMAP 'Ragged serving, next bricks' (a) — the per-row "
            "descriptor subsuming the cached bucket matrix is the "
            "next brick. Run the two drills separately until then.")
    if args.ragged and args.models:
        raise SystemExit("--ragged is a single-model drill knob (the "
                         "registry rungs keep the bucketed path)")
    metrics_path = (os.path.join(args.log_dir, "metrics.jsonl")
                    if args.log_dir else None)
    trace_path = args.trace_path
    if trace_path is None and args.trace_sample is not None:
        if not args.log_dir:
            raise SystemExit("--trace-sample needs --trace-path or "
                             "--log-dir (for the default "
                             "<log-dir>/spans.jsonl)")
        trace_path = os.path.join(args.log_dir, "spans.jsonl")
    trace_sample = (args.trace_sample if args.trace_sample is not None
                    else 1.0)
    if not 0.0 <= trace_sample <= 1.0:
        raise SystemExit(f"--trace-sample {trace_sample}: must be "
                         "in [0, 1]")
    if trace_path and args.models and args.chaos:
        raise SystemExit("--trace-path with --models --chaos is not "
                         "wired yet (each chaos round builds a fresh "
                         "registry/ledger and the shared spans file "
                         "would repeat trace ids) — trace the "
                         "single-model chaos or the plain registry "
                         "drill")
    if args.replicas > 1 or args.replica_ceiling:
        if args.models or args.chaos:
            raise SystemExit(
                "--replicas/--replica-ceiling drive the plain drill "
                "only for now (registry rungs size their own fleets "
                "via canary_fraction; the chaos harness stays "
                "single-engine) — drop --models/--chaos")
        if args.feature_cache:
            raise SystemExit(
                "--replicas with --feature-cache is not supported: "
                "the device-resident feature pool is single-engine "
                "state (a stream's cached activations live on ONE "
                "replica's device) — run the fleet without it")
    if args.hosts:
        if args.models:
            raise SystemExit(
                "--hosts drives the single-model drills only for now "
                "(the registry drill builds its engines internally) "
                "— drop --models")
        if args.ragged:
            raise SystemExit(
                "--hosts with --ragged is not supported: remote "
                "lanes speak the bucketed engine surface (see "
                "ROADMAP) — run the host drill without --ragged")
        if args.feature_cache:
            raise SystemExit(
                "--hosts with --feature-cache is not supported: the "
                "device-resident feature pool is single-engine state "
                "— run the host drill without it")
    if args.hosts_kill_one and not args.hosts:
        raise SystemExit("--hosts-kill-one needs --hosts")
    if args.hosts_kill_one and args.chaos:
        raise SystemExit("--hosts-kill-one drives the plain drill "
                         "(the chaos rounds inject their own host "
                         "faults via the widened site vocabulary)")
    if (args.guardian or args.admission_budget) and not args.models:
        raise SystemExit("--guardian/--admission-budget need --models "
                         "(they are ModelRegistry features)")
    if args.aot_cache and args.models:
        raise SystemExit("--aot-cache with --models is not wired yet "
                         "(the registry drill builds its engines "
                         "internally; use ModelRegistry's "
                         "artifact_dir= in library code) — run the "
                         "single-model drills against the cache dir")
    guardian_policy = None
    if args.guardian:
        guardian_policy = _parse_slo(args.slo) if args.slo else {}
        guardian_policy.setdefault("bake_window_s", args.bake_ms / 1e3)
        # size the evidence floor to the drill unless --slo pinned it:
        # the production default (min_requests=20) against a small
        # --requests run would hold past max_bake and roll every
        # clean canary back as insufficient_traffic
        guardian_policy.setdefault(
            "min_requests", max(1, min(20, args.requests // 8)))
    tiny = jnp.zeros((1, 64, 64, 3))

    if args.models:
        # multi-model registry drill: one live model per arch name,
        # optional canary rollout on the first, mixed priorities
        arch = {"basic": RAFTConfig(), "small": RAFTConfig(small=True)}
        models = []
        for name in args.models.split(","):
            c = arch.get(name)
            if c is None:
                raise SystemExit(f"--models {name!r}: choose from "
                                 f"{sorted(arch)}")
            m = RAFT(c)
            models.append((name, m.init(jax.random.PRNGKey(len(models)),
                                        tiny, tiny, iters=1), c))
        try:
            mix = tuple(int(v) for v in args.priority_mix.split(":"))
            if len(mix) != 2 or any(v < 0 for v in mix):
                raise ValueError
        except ValueError:
            raise SystemExit(
                f"--priority-mix {args.priority_mix!r}: expected "
                "INTERACTIVE:BATCH non-negative counts, e.g. 3:1 "
                "(0:0 = priority-less)")
        canary_variables = None
        if args.canary or args.chaos:
            # same arch as the first model, different init — the
            # "new checkpoint" the rollout ships
            canary_variables = RAFT(models[0][2]).init(
                jax.random.PRNGKey(97), tiny, tiny, iters=1)
        if args.chaos:
            summary = run_registry_chaos(
                models, shapes=shapes, rounds=args.chaos,
                requests=args.requests, submitters=args.submitters,
                bucket_batch=args.bucket_batch, iters=args.iters,
                priority_mix=mix,
                canary_fraction=args.canary or 0.5,
                canary_variables=canary_variables,
                dispatch_timeout_s=(args.dispatch_timeout_ms / 1e3
                                    if args.dispatch_timeout_ms
                                    else 0.4),
                hang_s=args.hang_ms / 1e3,
                breaker_failures=args.breaker_failures or 2,
                breaker_backoff_s=args.breaker_backoff_ms / 1e3,
                breaker_backoff_max_s=max(args.breaker_backoff_max_ms,
                                          args.breaker_backoff_ms) / 1e3,
                gather_window_s=args.gather_ms / 1e3,
                deadline_s=(args.deadline_ms / 1e3 if args.deadline_ms
                            else None),
                guardian=args.guardian,
                guardian_policy=guardian_policy,
                # scaled like the non-chaos path: a decision can only
                # land after the bake window — a fixed wait below it
                # would report every clean round guardian_undecided
                guardian_timeout_s=max(8.0, 4 * args.bake_ms / 1e3),
                admission_budget=args.admission_budget or None,
                admission_reserve=args.admission_reserve,
                max_queue=args.queue, seed=args.seed,
                metrics_path=metrics_path)
            print(json.dumps(summary), flush=True)
            if summary["violations"]:
                raise SystemExit(1)
            return
        summary = run_registry_drill(
            models, shapes=shapes, requests=args.requests,
            submitters=args.submitters, bucket_batch=args.bucket_batch,
            iters=args.iters, priority_mix=mix,
            canary_fraction=args.canary,
            canary_variables=canary_variables,
            sessions=args.sessions, session_frames=args.session_frames,
            deadline_s=(args.deadline_ms / 1e3 if args.deadline_ms
                        else None),
            max_queue=args.queue, gather_window_s=args.gather_ms / 1e3,
            dispatch_timeout_s=(args.dispatch_timeout_ms / 1e3
                                if args.dispatch_timeout_ms else None),
            breaker_failures=args.breaker_failures,
            breaker_backoff_s=args.breaker_backoff_ms / 1e3,
            breaker_backoff_max_s=max(args.breaker_backoff_max_ms,
                                      args.breaker_backoff_ms) / 1e3,
            wire=args.wire, pipeline_depth=args.pipeline_depth,
            guardian=args.guardian, guardian_policy=guardian_policy,
            guardian_timeout_s=max(30.0, 8 * args.bake_ms / 1e3),
            admission_budget=args.admission_budget or None,
            admission_reserve=args.admission_reserve,
            metrics_path=metrics_path, trace_path=trace_path,
            trace_sample=trace_sample, seed=args.seed)
        print(json.dumps(summary), flush=True)
        return

    cfg = RAFTConfig(small=args.small)
    model = RAFT(cfg)
    # params are shape-independent: init tiny (infer_bench lesson)
    variables = model.init(jax.random.PRNGKey(0), tiny, tiny, iters=1)
    if args.chaos:
        summary = run_chaos_drill(
            variables, cfg, shapes=shapes, rounds=args.chaos,
            requests=args.requests, submitters=args.submitters,
            bucket_batch=args.bucket_batch, iters=args.iters,
            dispatch_timeout_s=(args.dispatch_timeout_ms / 1e3
                                if args.dispatch_timeout_ms else 0.4),
            hang_s=args.hang_ms / 1e3,
            breaker_failures=args.breaker_failures or 2,
            breaker_backoff_s=args.breaker_backoff_ms / 1e3,
            breaker_backoff_max_s=max(args.breaker_backoff_max_ms,
                                      args.breaker_backoff_ms) / 1e3,
            recover_s=args.recover_s or 8.0,
            gather_window_s=args.gather_ms / 1e3,
            deadline_s=(args.deadline_ms / 1e3 if args.deadline_ms
                        else None),
            wire=args.wire, pipeline_depth=args.pipeline_depth,
            sessions=args.sessions, session_frames=args.session_frames,
            session_device_state=args.device_state,
            feature_cache=args.feature_cache,
            cache_capacity=args.cache_capacity,
            ragged=args.ragged, capacity_classes=capacity_classes,
            max_queue=args.queue, seed=args.seed,
            metrics_path=metrics_path, trace_path=trace_path,
            trace_sample=trace_sample, aot_cache=args.aot_cache,
            hosts=args.hosts)
        print(json.dumps(summary), flush=True)
        if summary["violations"]:
            raise SystemExit(1)
        return
    summary = run_drill(
        variables, cfg, shapes=shapes, requests=args.requests,
        submitters=args.submitters, bucket_batch=args.bucket_batch,
        iters=args.iters, sessions=args.sessions,
        session_frames=args.session_frames,
        deadline_s=args.deadline_ms / 1e3 if args.deadline_ms else None,
        max_queue=args.queue, gather_window_s=args.gather_ms / 1e3,
        dispatch_timeout_s=(args.dispatch_timeout_ms / 1e3
                            if args.dispatch_timeout_ms else None),
        breaker_failures=args.breaker_failures,
        breaker_backoff_s=args.breaker_backoff_ms / 1e3,
        breaker_backoff_max_s=max(args.breaker_backoff_max_ms,
                                  args.breaker_backoff_ms) / 1e3,
        wire=args.wire, pipeline_depth=args.pipeline_depth,
        session_device_state=args.device_state,
        feature_cache=args.feature_cache,
        cache_capacity=args.cache_capacity,
        ragged=args.ragged, capacity_classes=capacity_classes,
        recover_s=args.recover_s,
        metrics_path=metrics_path, trace_path=trace_path,
        trace_sample=trace_sample, seed=args.seed,
        aot_cache=args.aot_cache,
        replicas=args.replicas, replica_ceiling=args.replica_ceiling,
        hosts=args.hosts, host_kill_one=args.hosts_kill_one)
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
