"""Synthetic ragged-traffic drill for the serving front-end.

Drives ``MicroBatchScheduler`` + a warm-start ``RAFTEngine`` with
mixed-shape traffic from concurrent submitter threads (plus optional
per-stream video sessions) and prints ONE JSON summary line — the
serving-side counterpart of bench.py's one-line contract, and the
harness the tier-1 acceptance drill (tests/test_scheduler.py) runs at
tiny shapes. The request mix is deliberately ragged: per-shape totals
that don't divide the bucket batch leave a tail the scheduler must
batch-fill into the SAME executables the full micro-batches used.

Run on the real chip (cvt2trt-ish shapes):
    python -m raft_tpu.cli.serve_bench --shapes 440x1024,368x496 \\
        --requests 48 --submitters 2 --bucket-batch 4
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time


def _ceil8(x: int) -> int:
    return -(-x // 8) * 8


def run_drill(variables, cfg, *, shapes, requests=32, submitters=2,
              bucket_batch=4, iters=2, sessions=0, session_frames=4,
              deadline_s=None, max_queue=64, gather_window_s=0.005,
              metrics_path=None, seed=0, engine=None):
    """The drill as a library call (tests reuse it, and may pass a
    prebuilt warm-start ``engine`` to share compiles across drills).
    Returns the summary dict the CLI prints."""
    import numpy as np

    from raft_tpu.serving.engine import RAFTEngine
    from raft_tpu.serving.scheduler import (BackpressureError,
                                            DeadlineExceeded,
                                            MicroBatchScheduler)
    from raft_tpu.serving.session import VideoSession

    if engine is None:
        # one documented bucket per distinct ÷8-padded request shape
        envelope = sorted({(bucket_batch, _ceil8(h), _ceil8(w))
                           for h, w in shapes})
        engine = RAFTEngine(variables, cfg, iters=iters,
                            envelope=envelope, precompile=True,
                            warm_start=True)
    documented = len(engine._compiled)
    sched = MicroBatchScheduler(engine, max_queue=max_queue,
                                max_batch=bucket_batch,
                                gather_window_s=gather_window_s,
                                metrics_path=metrics_path)
    futures = [[] for _ in range(submitters)]
    shed = [0] * submitters
    session_stats = {"pairs": 0, "warm": 0, "errors": 0}

    def submit_loop(sid):
        rng = np.random.RandomState(seed + sid)
        per = requests // submitters + (1 if sid < requests % submitters
                                        else 0)
        for k in range(per):
            h, w = shapes[(sid + k) % len(shapes)]
            i1 = rng.rand(h, w, 3).astype(np.float32) * 255
            i2 = rng.rand(h, w, 3).astype(np.float32) * 255
            try:
                futures[sid].append(
                    sched.submit(i1, i2, deadline_s=deadline_s))
            except BackpressureError:
                shed[sid] += 1

    def session_loop(sid):
        rng = np.random.RandomState(seed + 1000 + sid)
        h, w = shapes[sid % len(shapes)]
        sess = VideoSession(sched, deadline_s=deadline_s)
        futs = [sess.submit_frame(rng.rand(h, w, 3).astype(np.float32)
                                  * 255)
                for _ in range(session_frames + 1)]
        for f in futs:
            if f is None:
                continue
            try:
                f.result(timeout=600)
                session_stats["pairs"] += 1
            except Exception:
                session_stats["errors"] += 1
        session_stats["warm"] += sess.warm_submits

    threads = ([threading.Thread(target=submit_loop, args=(s,))
                for s in range(submitters)]
               + [threading.Thread(target=session_loop, args=(s,))
                  for s in range(sessions)])
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sched.close(drain=True)          # finishes every accepted request
    wall = time.perf_counter() - t0

    served = deadline_missed = errors = 0
    for fl in futures:
        for fut in fl:
            try:
                fut.result(timeout=0)  # close() drained: all settled
                served += 1
            except DeadlineExceeded:
                deadline_missed += 1
            except Exception:
                errors += 1
    rec = sched.metrics.snapshot(executables=len(engine._compiled))
    total_served = served + session_stats["pairs"]
    occ = rec["occupancy"]
    return {
        "submitted": rec["submitted"],
        "served": served,
        "shed": sum(shed),
        "deadline_missed": deadline_missed,
        "errors": errors + session_stats["errors"],
        "abandoned_inflight": rec["abandoned_inflight"],
        "dispatches": rec["dispatches"],
        "executables": len(engine._compiled),
        "documented_buckets": documented,
        "mean_occupancy": occ["mean"],
        "baseline_occupancy": occ["one_per_dispatch_baseline"],
        "session_pairs": session_stats["pairs"],
        "warm_submits": session_stats["warm"],
        "p50_ms": rec["latency"]["p50_ms"],
        "p99_ms": rec["latency"]["p99_ms"],
        "wall_s": round(wall, 3),
        "pairs_per_s": round(total_served / wall, 2) if wall else 0.0,
    }


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(
        description="serving front-end ragged-traffic drill")
    p.add_argument("--shapes", default="64x64,48x48",
                   help="comma list of HxW request shapes (the mixed "
                        "traffic); one bucket per distinct ÷8 shape")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--submitters", type=int, default=2)
    p.add_argument("--bucket-batch", type=int, default=4,
                   help="bucket batch dim = coalescing ceiling")
    p.add_argument("--sessions", type=int, default=0,
                   help="concurrent warm-start video sessions")
    p.add_argument("--session-frames", type=int, default=4)
    p.add_argument("--deadline-ms", type=float, default=0,
                   help="per-request deadline (0: none)")
    p.add_argument("--queue", type=int, default=64)
    p.add_argument("--gather-ms", type=float, default=5.0)
    p.add_argument("--iters", type=int, default=20,
                   help="refinement iterations (export bakes 20)")
    p.add_argument("--small", action="store_true")
    p.add_argument("--log-dir", default=None,
                   help="append the metrics snapshot to "
                        "<log-dir>/metrics.jsonl")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT

    shapes = [tuple(int(v) for v in s.split("x"))
              for s in args.shapes.split(",")]
    cfg = RAFTConfig(small=args.small)
    model = RAFT(cfg)
    # params are shape-independent: init tiny (infer_bench lesson)
    tiny = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), tiny, tiny, iters=1)
    metrics_path = (os.path.join(args.log_dir, "metrics.jsonl")
                    if args.log_dir else None)
    summary = run_drill(
        variables, cfg, shapes=shapes, requests=args.requests,
        submitters=args.submitters, bucket_batch=args.bucket_batch,
        iters=args.iters, sessions=args.sessions,
        session_frames=args.session_frames,
        deadline_s=args.deadline_ms / 1e3 if args.deadline_ms else None,
        max_queue=args.queue, gather_window_s=args.gather_ms / 1e3,
        metrics_path=metrics_path, seed=args.seed)
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
