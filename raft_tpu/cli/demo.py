"""Demo CLI — the ``demo.py`` analog, headless.

Globs a frame directory, runs consecutive-pair flow at ``iters=20``
(demo.py:62), and writes side-by-side image/flow PNGs instead of
``cv2.imshow`` (demo.py:26-39) so it runs on TPU VMs without a display.
Keeps the fork's fixed color normalization (rad=3,
core/utils/flow_viz.py:128-130) so colors are frame-to-frame consistent.
"""

from __future__ import annotations

import argparse
import glob
import os

import numpy as np
from PIL import Image

import jax.numpy as jnp

from raft_tpu.cli._args import add_corr_args, corr_overrides
from raft_tpu.config import ITERS_DEMO, RAFTConfig
from raft_tpu.ops.padding import InputPadder
from raft_tpu.utils.flow_viz import flow_to_image


def load_image(path: str) -> jnp.ndarray:
    """PIL -> (1, H, W, 3) float32 device array (demo.py:20-23)."""
    img = np.array(Image.open(path)).astype(np.uint8)
    return jnp.asarray(img, jnp.float32)[None]


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(description="RAFT demo on a frame directory")
    p.add_argument("--model", required=True, help=".pth or .msgpack weights")
    p.add_argument("--path", required=True, help="directory of frames")
    p.add_argument("--out", default="demo_out", help="output directory")
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--alternate_corr", action="store_true")
    add_corr_args(p)
    p.add_argument("--iters", type=int, default=ITERS_DEMO)
    args = p.parse_args(argv)

    from raft_tpu.evaluation.evaluate import make_forward
    from raft_tpu.training.trainer import load_weights

    cfg = RAFTConfig(small=args.small, mixed_precision=args.mixed_precision,
                     alternate_corr=args.alternate_corr,
                     **corr_overrides(args))
    variables = load_weights(args.model, cfg)
    fwd, _ = make_forward(cfg, args.iters)

    images = sorted(glob.glob(os.path.join(args.path, "*.png"))
                    + glob.glob(os.path.join(args.path, "*.jpg")))
    os.makedirs(args.out, exist_ok=True)

    for imfile1, imfile2 in zip(images[:-1], images[1:]):
        image1 = load_image(imfile1)
        image2 = load_image(imfile2)
        padder = InputPadder(image1.shape)
        im1, im2 = padder.pad(image1, image2)
        _, flow_up = fwd(variables, im1, im2)
        flow = np.asarray(padder.unpad(flow_up)[0])

        # side-by-side frame/flow, the viz() analog (demo.py:26-39)
        img = np.asarray(image1[0]).astype(np.uint8)
        flo = flow_to_image(flow)
        pair = np.concatenate([img, flo], axis=0)
        name = os.path.splitext(os.path.basename(imfile1))[0] + "_flow.png"
        Image.fromarray(pair).save(os.path.join(args.out, name))
        print(f"{imfile1} -> {os.path.join(args.out, name)}", flush=True)


if __name__ == "__main__":
    main()
