"""Read back spans.jsonl: trace timelines + where-did-the-p99-go.

The serving stack's request tracing (serving/trace.py) writes one
span record per accepted request (plus fan-in dispatch spans) — this
tool is the read side:

- the default report answers the operator question "where did the p99
  go": outcome-class counts, the latency histogram's top-bucket
  membership among retained spans, and a **phase-attribution table
  over the tail exemplars** (queue vs assembly vs device vs fetch —
  which stage of the pipeline actually ate the slow requests' time),
  with each exemplar's dominant phase and annotations (coalesce
  fan-in, cache hit/miss, breaker state at admit, canary assignment);
- ``--trace ID`` reconstructs one trace's timeline: the span's phase
  marks, its linked dispatch span (the micro-batch it rode, who else
  rode it, the padding share), and the session chain walked through
  ``parent`` links back to the stream's first frame.

Usage::

    python -m raft_tpu.cli.serve_trace /tmp/serve/spans.jsonl
    python -m raft_tpu.cli.serve_trace spans.jsonl --trace r-17
    python -m raft_tpu.cli.serve_trace spans.jsonl --all --top 10

No jax anywhere on this path — the only raft_tpu import is the
(jax-free) metrics module's histogram ladder, so the tool runs
wherever the jsonl files land.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional

#: attribution columns, in pipeline order (serving/trace._phases)
PHASES = ("queue_ms", "assembly_ms", "device_ms", "fetch_ms")

_LADDER = None


def _hist_idx(span: Dict) -> int:
    """The latency-histogram bucket this span's completion was binned
    into: ``observed_ms`` is the exact value ServingMetrics observed
    (the span's own close clock runs ms later), binned by the
    histogram's own ``bucket_idx`` — one definition, no drift."""
    global _LADDER
    if _LADDER is None:
        from raft_tpu.serving.metrics import LatencyHistogram
        _LADDER = LatencyHistogram()
    return _LADDER.bucket_idx(span.get("observed_ms",
                                       span.get("total_ms", 0.0)))


def load_spans(path: str) -> List[Dict]:
    """Parse spans.jsonl; skips non-span lines (a shared file is
    tolerated) and unparseable lines (a torn tail write must not kill
    the report)."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "span":
                out.append(rec)
    return out


def request_spans(spans: List[Dict]) -> List[Dict]:
    return [s for s in spans if s.get("span") == "request"]


def tail_spans(spans: List[Dict]) -> List[Dict]:
    """The retained tail exemplars in the histogram's FINAL top
    occupied bucket. The ``tail`` flag ratchets at write time (an
    early fast completion is trivially "top so far" and stays
    retained), so membership re-derives here: among tail-flagged
    request spans, keep those binned into the max occupied bucket —
    the same filter the metrics snapshot's ``tail_exemplars`` refs
    apply."""
    tails = [s for s in request_spans(spans) if s.get("tail")]
    if not tails:
        return tails
    top = max(_hist_idx(s) for s in tails)
    return [s for s in tails if _hist_idx(s) == top]


def phase_attribution(spans: List[Dict],
                      tail_only: bool = True) -> Dict:
    """The p99-attribution table: per phase, total/mean ms and the
    share of the selected spans' wall time. ``tail_only`` selects the
    tail exemplars (falling back to every completed request span when
    none are flagged — e.g. a drill too uniform to have a tail)."""
    sel = tail_spans(spans) if tail_only else []
    if not sel:
        sel = [s for s in request_spans(spans)
               if s.get("class") == "completed"]
    if not sel:
        return {"spans": 0, "total_ms": 0.0, "phases": {}}
    totals = {p: 0.0 for p in PHASES}
    wall = 0.0
    for s in sel:
        wall += s.get("total_ms", 0.0)
        for p, v in (s.get("phases") or {}).items():
            if p in totals:
                totals[p] += v
    n = len(sel)
    return {
        "spans": n,
        "total_ms": round(wall, 3),
        "mean_ms": round(wall / n, 3),
        "phases": {
            p: {"total_ms": round(t, 3),
                "mean_ms": round(t / n, 3),
                "share": round(t / wall, 4) if wall else 0.0}
            for p, t in totals.items()},
    }


def dominant_phase(span: Dict) -> Optional[str]:
    ph = span.get("phases") or {}
    known = {p: v for p, v in ph.items() if p in PHASES}
    if not known:
        return None
    return max(known, key=known.get)


def top_bucket_membership(spans: List[Dict]) -> Dict:
    """Which retained request spans sit in the latency histogram's top
    occupied region: the tail-flagged spans, their max total_ms, and
    their trace ids — the membership the metrics snapshot's
    ``tail_exemplars`` refs must resolve against."""
    tails = tail_spans(spans)
    return {
        "count": len(tails),
        "trace_ids": [s["trace_id"] for s in tails],
        "max_ms": max((s.get("total_ms", 0.0) for s in tails),
                      default=0.0),
    }


def find(spans: List[Dict], trace_id: str) -> Optional[Dict]:
    for s in spans:
        if s.get("trace_id") == trace_id:
            return s
    return None


def timeline(spans: List[Dict], trace_id: str,
             max_chain: int = 64) -> Dict:
    """One trace reconstructed: the span, its dispatch span (the
    micro-batch fan-in it rode), and the session chain walked back
    through ``parent`` links (bounded; a cycle or a pruned parent
    terminates the walk cleanly — a sampled-out ancestor is reported
    as such, not an error)."""
    span = find(spans, trace_id)
    if span is None:
        return {"trace_id": trace_id, "found": False}
    dispatch = (find(spans, span["dispatch"])
                if span.get("dispatch") else None)
    chain: List[Dict] = []
    seen = {trace_id}
    cur = span
    truncated = False
    while cur is not None and cur.get("parent"):
        if len(chain) >= max_chain:
            truncated = True      # cap hit: the stream goes on back
            break
        pid = cur["parent"]
        if pid in seen:
            truncated = True      # defensive: a cycle ends the walk
            break
        seen.add(pid)
        parent = find(spans, pid)
        if parent is None:
            chain.append({"trace_id": pid, "retained": False})
            break                 # sampled out / rotated away
        chain.append(parent)
        cur = parent
    return {"trace_id": trace_id, "found": True, "span": span,
            "dispatch": dispatch, "chain": chain,
            "chain_truncated": truncated}


def _fmt_ms(v) -> str:
    return f"{v:9.3f}" if isinstance(v, (int, float)) else f"{'-':>9}"


def print_timeline(tl: Dict) -> None:
    if not tl.get("found"):
        print(f"trace {tl['trace_id']}: not found (sampled out, or "
              "wrong file?)")
        return
    s = tl["span"]
    print(f"trace {s['trace_id']}  [{s.get('class', '?')}] "
          f"outcome={s.get('outcome')} bucket={s.get('bucket')} "
          f"total={s.get('total_ms')}ms tail={s.get('tail')}")
    meta = {k: s[k] for k in ("model", "variant", "canary", "priority",
                              "stream", "seq", "prime", "cache",
                              "warm", "breaker_at_admit", "reason",
                              "deadline_s") if k in s}
    if meta:
        print(f"  {json.dumps(meta)}")
    ph = s.get("phases") or {}
    if ph:
        print("  phase        ms")
        for p in PHASES:
            if p in ph:
                print(f"  {p:<12}{_fmt_ms(ph[p])}")
    d = tl.get("dispatch")
    if d is not None:
        print(f"  dispatch {d['trace_id']}: fan_in={d.get('fan_in')} "
              f"capacity={d.get('capacity')} "
              f"padding_waste={d.get('padding_waste')} "
              f"bucket={d.get('bucket')}")
        others = [r for r in d.get("requests", [])
                  if r != s["trace_id"]]
        if others:
            print(f"    coalesced with: {', '.join(others)}")
    if tl["chain"]:
        print("  session chain (newest -> oldest):")
        for p in tl["chain"]:
            if not p.get("retained", True):
                print(f"    {p['trace_id']}  (not retained — sampled "
                      "out)")
                continue
            print(f"    {p['trace_id']}  [{p.get('class', '?')}] "
                  f"{p.get('total_ms')}ms seq={p.get('seq', '-')} "
                  f"prime={p.get('prime', False)} "
                  f"cache={p.get('cache', '-')}")


def print_report(spans: List[Dict], top: int,
                 tail_only: bool = True) -> None:
    reqs = request_spans(spans)
    by_class: Dict[str, int] = {}
    for s in reqs:
        by_class[s.get("class", "?")] = \
            by_class.get(s.get("class", "?"), 0) + 1
    n_disp = sum(1 for s in spans if s.get("span") == "dispatch")
    print(f"{len(reqs)} request spans ({n_disp} dispatch spans) "
          f"by class: {json.dumps(by_class, sort_keys=True)}")
    membership = top_bucket_membership(spans)
    print(f"top-bucket membership: {membership['count']} tail "
          f"exemplars, max {membership['max_ms']}ms")

    attr = phase_attribution(spans, tail_only=tail_only)
    scope = ("tail exemplars" if tail_only and tail_spans(spans)
             else "all completed spans")
    print(f"\n== where did the p99 go: phase attribution over "
          f"{attr['spans']} {scope} ==")
    for p in PHASES:
        blk = attr["phases"].get(p)
        if blk is None:
            continue
        print(f"{blk['share'] * 100:6.1f}%  mean {_fmt_ms(blk['mean_ms'])} ms  {p}")

    sel = tail_spans(spans) or reqs
    sel = sorted(sel, key=lambda s: -s.get("total_ms", 0.0))[:top]
    if sel:
        print(f"\n== top {len(sel)} slowest retained spans ==")
        for s in sel:
            notes = []
            dom = dominant_phase(s)
            if dom:
                notes.append(f"dominant={dom}")
            for k in ("cache", "breaker_at_admit", "canary", "fan_in",
                      "reason"):
                if k in s and s[k] not in (None, False, "closed"):
                    notes.append(f"{k}={s[k]}")
            print(f"{_fmt_ms(s.get('total_ms'))} ms  {s['trace_id']:<8} "
                  f"[{s.get('class', '?')}] {s.get('bucket', '?'):<16} "
                  f"{' '.join(notes)}")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="reconstruct request traces / attribute tail "
                    "latency from spans.jsonl")
    p.add_argument("spans", help="spans.jsonl written by a traced "
                                 "scheduler/registry")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="reconstruct one trace's timeline (span + "
                        "dispatch fan-in + session chain)")
    p.add_argument("--top", type=int, default=15,
                   help="slowest-span table size")
    p.add_argument("--all", action="store_true",
                   help="attribute over every completed span, not "
                        "just the tail exemplars")
    args = p.parse_args(argv)

    if not os.path.exists(args.spans):
        raise SystemExit(f"no such spans file: {args.spans}")
    spans = load_spans(args.spans)
    if not spans:
        raise SystemExit(f"{args.spans}: no span records — was "
                         "tracing armed (--trace-path / trace_path=)?")
    if args.trace:
        print_timeline(timeline(spans, args.trace))
        return
    print_report(spans, args.top, tail_only=not args.all)


if __name__ == "__main__":
    main()
