"""Evaluation CLI — the ``evaluate.py:169-195`` analog."""

from __future__ import annotations

import argparse

from raft_tpu.cli._args import add_corr_args, corr_overrides
from raft_tpu.config import RAFTConfig


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(description="Validate RAFT checkpoints")
    p.add_argument("--model", required=True, help=".pth or .msgpack weights")
    p.add_argument("--dataset", required=True,
                   choices=["chairs", "sintel", "kitti"])
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--alternate_corr", action="store_true")
    add_corr_args(p)
    p.add_argument("--data_root", default="datasets")
    p.add_argument("--submission", action="store_true",
                   help="write a leaderboard submission instead of validating")
    p.add_argument("--eval_batch", type=int, default=4,
                   help="pairs per forward for uniform-size datasets "
                        "(chairs/sintel); 1 = reference's per-image loop")
    args = p.parse_args(argv)

    from raft_tpu.evaluation import evaluate as ev
    from raft_tpu.training.trainer import load_weights

    cfg = RAFTConfig(small=args.small, mixed_precision=args.mixed_precision,
                     alternate_corr=args.alternate_corr,
                     **corr_overrides(args))
    variables = load_weights(args.model, cfg)

    if args.submission:
        if args.dataset == "sintel":
            ev.create_sintel_submission(variables, cfg, warm_start=True,
                                        data_root=args.data_root)
        elif args.dataset == "kitti":
            ev.create_kitti_submission(variables, cfg,
                                       data_root=args.data_root)
        else:
            p.error("submissions exist for sintel/kitti only")
        return

    fn = {"chairs": ev.validate_chairs, "sintel": ev.validate_sintel,
          "kitti": ev.validate_kitti}[args.dataset]
    kwargs = {}
    if args.dataset in ("chairs", "sintel"):
        kwargs["batch_size"] = args.eval_batch
    results = fn(variables, cfg, data_root=args.data_root, **kwargs)
    print(results)


if __name__ == "__main__":
    main()
