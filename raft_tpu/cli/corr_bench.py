"""Correlation-lookup benchmark + parity harness.

The ``test_trt.py:52-99`` discipline (same inputs, two backends, numeric
diff + wall-clock with explicit fences) applied to the corr-lookup backends:

- ``gather``: flattened-index 4-corner take_along_axis (XLA)
- ``onehot``: one-hot window GEMMs on the MXU (XLA)
- ``softsel``: one-hot GEMMs with the bilinear lerp folded into the
  selection matrices (no post-GEMM lerp chain)
- ``onehot_t``: one-hot GEMMs over the transposed pixels-on-lanes pyramid
- ``pallas``: block-pipelined mask-select kernel (TPU only; see
  ``kernels/corr_pallas.py`` for the design and its measured history)
- ``alt``:    on-the-fly blockwise correlation (alt_cuda_corr analog, XLA)
- ``alt_pallas``: on-the-fly windowed correlation, window-DMA-ring Pallas
  kernel (``kernels/corr_alt_pallas.py``; TPU only)

Run on the real chip:  python -m raft_tpu.cli.corr_bench --hw 46 62
(46x62 = the 368x496 chairs crop at stride 8; use 128 128 for the KITTI/TRT
max envelope).
"""

from __future__ import annotations

import argparse
import sys
import warnings

import numpy as np

import jax
import jax.numpy as jnp


def bench_fn(fn, coords, vols, iters=20):
    """Time ``iters`` applications of ``fn(coords, vols)`` inside ONE
    executable (see raft_tpu/utils/timing.py for the remote-backend
    fencing scheme) and return (seconds/iter, one full output for parity
    comparison). ``vols`` flows as a jit argument — closing over a
    volume embeds it in the HLO as a literal constant, which the remote
    compile endpoint rejects above ~hundreds of MB (HTTP 413)."""
    from raft_tpu.utils.timing import chain_timed

    out = jax.tree_util.tree_map(np.asarray,
                                 jax.jit(fn)(coords, vols))  # parity, untimed
    return chain_timed(fn, coords, iters, vols), out


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(description="corr lookup backend shootout")
    p.add_argument("--batch", type=int, default=6)
    p.add_argument("--hw", type=int, nargs=2, default=[46, 62],
                   help="feature-map H W (input/8)")
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--radius", type=int, default=4)
    p.add_argument("--levels", type=int, default=4)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--impls", nargs="+",
                   default=["gather", "onehot", "onehot_t", "softsel", "pallas",
                            "alt", "alt_pallas"])
    p.add_argument("--grad", action="store_true",
                   help="bench value+grad (the train-step cost) instead of "
                        "forward only")
    p.add_argument("--corr_dtype", "--corr-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="volume storage dtype for the materialized-pyramid "
                        "impls (gather/onehot/pallas) — isolates the "
                        "halved-traffic bf16 lever at lookup granularity; "
                        "alt paths sample fmaps directly and are unaffected")
    args = p.parse_args(argv)

    from raft_tpu.kernels import (alt_corr_lookup_pallas, corr_lookup_pallas,
                                  pad_f2_pyramid, pad_pyramid,
                                  pallas_available)
    from raft_tpu.models.corr import (alt_corr_lookup, build_corr_pyramid,
                                      build_corr_pyramid_t, corr_lookup,
                                      corr_lookup_onehot,
                                      corr_lookup_onehot_t,
                                      corr_lookup_softsel,
                                      corr_lookup_softsel_t)
    from raft_tpu.ops.pooling import avg_pool2x2

    B, (H, W), C = args.batch, args.hw, args.dim
    rng = np.random.RandomState(0)
    fmap1 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    fmap2 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    base = np.stack(np.meshgrid(np.arange(W), np.arange(H)), -1)
    coords = jnp.asarray(base[None].astype(np.float32)
                         + rng.randn(B, H, W, 2).astype(np.float32) * 4)

    pyramid = jax.block_until_ready(
        tuple(build_corr_pyramid(fmap1, fmap2, args.levels)))
    if args.corr_dtype != "float32":
        pyramid = jax.block_until_ready(tuple(
            v.astype(args.corr_dtype) for v in pyramid))
    # the model pads once OUTSIDE the refinement loop (raft.py wires
    # prepadded=True); bench the same configuration
    pyramid_pp = jax.block_until_ready(
        tuple(pad_pyramid(pyramid, args.radius)))
    f2_pyr = [fmap2]
    for _ in range(args.levels - 1):
        f2_pyr.append(avg_pool2x2(f2_pyr[-1]))
    f2_pyr = jax.block_until_ready(tuple(f2_pyr))

    from raft_tpu.kernels.corr_pallas import _pad

    PAD = _pad(args.radius)  # pad_pyramid margin, single source of truth

    def unpad_grads(d_pp):
        """Padded-pyramid cotangents -> unpadded layout (adjoint of pad)."""
        return tuple(
            d[:, :v.shape[1], PAD:PAD + v.shape[2], PAD:PAD + v.shape[3]]
            for d, v in zip(d_pp, pyramid))

    def transpose_grads(d_t):
        """onehot_t cotangents (B,Hl,Wl,N) -> the (B,N,Hl,Wl) layout the
        other volume impls produce, so grad-mode parity compares
        like-with-like (a raveled permuted layout reads as rel diff ~1)."""
        return tuple(jnp.transpose(d, (0, 3, 1, 2)) for d in d_t)

    # built only when requested: the extra transposed pyramid costs full
    # volume memory and can shift OOM behavior of other impls' runs
    pyramid_t = (jax.block_until_ready(tuple(
        v.astype(args.corr_dtype) for v in
        build_corr_pyramid_t(fmap1, fmap2, args.levels)))
        if {"onehot_t", "softsel_t"} & set(args.impls) else None)

    # per impl: (volume input to differentiate, lookup fn, grad postprocess)
    impls = {
        "gather": (pyramid,
                   lambda v, c: corr_lookup(v, c, args.radius), None),
        "onehot": (pyramid,
                   lambda v, c: corr_lookup_onehot(v, c, args.radius), None),
        "softsel": (pyramid,
                    lambda v, c: corr_lookup_softsel(v, c, args.radius),
                    None),
        "onehot_t": (pyramid_t,
                     lambda v, c: corr_lookup_onehot_t(v, c, args.radius),
                     transpose_grads),
        "softsel_t": (pyramid_t,
                      lambda v, c: corr_lookup_softsel_t(v, c, args.radius),
                      transpose_grads),
        "pallas": (pyramid_pp,
                   lambda v, c: corr_lookup_pallas(
                       v, c, args.radius, prepadded=True), unpad_grads),
        "alt": ((fmap1, f2_pyr),
                lambda v, c: alt_corr_lookup(v[0], v[1], c, args.radius),
                None),
        "alt_pallas": ((fmap1, jax.block_until_ready(
                            tuple(pad_f2_pyramid(f2_pyr, args.radius)))),
                       lambda v, c: alt_corr_lookup_pallas(
                           v[0], v[1], c, args.radius, prepadded=True),
                       None),
    }

    lookups = {}
    for name, (vols, fn, post) in impls.items():
        if args.grad:
            # Training cost: grads flow into the corr volume / fmaps (coords
            # are stop_gradient'ed each refinement iteration, raft.py loop),
            # so differentiate w.r.t. the volume inputs, not coords.
            def run(c, vols, _fn=fn, _post=post):
                val, d = jax.value_and_grad(
                    lambda v: jnp.sum(_fn(v, c) ** 2))(vols)
                return val, (_post(d) if _post else d)
        else:
            def run(c, vols, _fn=fn):
                return _fn(vols, c)
        lookups[name] = (run, vols)

    # Known-crashing cell (CRASH_BISECT_r05.log): gather's bf16 backward
    # (scatter lowering) takes down the TPU worker, and a dead worker
    # fails every impl queued after it in the same process — the r3
    # shootout lost its onehot/onehot_t rows exactly this way. Warn and
    # run gather LAST so one crashing backend can't invalidate the rest.
    run_order = list(args.impls)
    if (args.grad and args.corr_dtype == "bfloat16" and "gather" in run_order
            and len(run_order) > 1):
        warnings.warn(
            "gather+grad+bfloat16 is a known TPU-worker-crashing cell "
            "(CRASH_BISECT_r05.log); reordering it last so the other "
            "impls' rows land first", stacklevel=1)
        run_order = [n for n in run_order if n != "gather"] + ["gather"]

    reference = None
    results = {}
    failed = []
    for name in run_order:
        if name not in impls:
            print(f"{name:>8}: unknown impl (choose from "
                  f"{', '.join(impls)})")
            failed.append(name)  # a typo'd runbook row must not exit 0
            continue
        if name in ("pallas", "alt_pallas") and not pallas_available():
            print(f"{name:>8}: skipped (no TPU backend)")
            continue
        try:
            run, vols = lookups[name]
            dt, out = bench_fn(run, coords, vols, iters=args.iters)
        except Exception as e:
            print(f"{name:>8}: FAILED {type(e).__name__}: {e}")
            failed.append(name)
            continue
        # comparable output: the lookup itself, or — in grad mode — the
        # sum-of-squares primal plus every gradient leaf, flattened (a
        # wrong backward must not hide behind a correct forward). Note
        # 'alt' differentiates the fmaps instead of the volume, so its
        # grad-mode diff vs the volume-based impls is structural, not a bug.
        if args.grad:
            val, grads = out
            # grads normalized SEPARATELY from the primal: the primal is
            # a sum of squares orders of magnitude above any gradient
            # entry, and a shared max-|reference| denominator once hid a
            # fully permuted gradient layout behind a ~1e-5 "diff"
            cmp = np.concatenate(
                [np.ravel(l) for l in jax.tree_util.tree_leaves(grads)])
            cmp_primal = float(np.ravel(val)[0])
        else:
            cmp = np.asarray(out)
            cmp_primal = None
        if reference is None:
            reference = (cmp, cmp_primal)
            diff = "max|Δ|=0.00e+00"
        elif cmp.shape != reference[0].shape:
            # 'alt' differentiates (fmap1, f2_pyr) while the volume impls
            # differentiate the pyramid — gradient vectors aren't
            # comparable across that boundary
            diff = "Δ=n/a (different grad structure)"
        else:
            ref, ref_primal = reference
            denom = (max(float(np.abs(ref).max()), 1e-9)
                     if args.grad else 1.0)
            diff = f"max|Δ|={float(np.abs(cmp - ref).max()) / denom:.2e}"
            if args.grad:
                prim_rel = (abs(cmp_primal - ref_primal)
                            / max(abs(ref_primal), 1e-9))
                diff += f" primalΔ={prim_rel:.1e}"
        results[name] = dt
        queries_per_s = B * H * W / dt
        print(f"{name:>8}: {dt * 1e3:8.3f} ms  "
              f"{queries_per_s / 1e6:8.2f} Mquery/s  {diff}")

    if results:
        fastest = min(results, key=results.get)
        print(f"fastest: {fastest}")
    return results, failed


if __name__ == "__main__":
    # a run where any REQUESTED impl failed must exit nonzero — runbook
    # markers treat exit 0 as "measured", and a worker crash that failed
    # every impl once masqueraded as a completed shootout row
    _, _failed = main()
    sys.exit(1 if _failed else 0)
