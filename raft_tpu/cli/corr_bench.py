"""Correlation-lookup benchmark + parity harness.

The ``test_trt.py:52-99`` discipline (same inputs, two backends, numeric
diff + wall-clock with explicit fences) applied to the corr-lookup backends:

- ``gather``: flattened-index 4-corner take_along_axis (XLA)
- ``onehot``: one-hot window GEMMs on the MXU (XLA)
- ``pallas``: double-buffered window-DMA kernel (TPU only)
- ``alt``:    on-the-fly blockwise correlation (alt_cuda_corr analog)

Run on the real chip:  python -m raft_tpu.cli.corr_bench --hw 46 62
(46x62 = the 368x496 chairs crop at stride 8; use 128 128 for the KITTI/TRT
max envelope).
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp


def bench_fn(fn, args, warmup=2, iters=20):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def main(argv=None):
    p = argparse.ArgumentParser(description="corr lookup backend shootout")
    p.add_argument("--batch", type=int, default=6)
    p.add_argument("--hw", type=int, nargs=2, default=[46, 62],
                   help="feature-map H W (input/8)")
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--radius", type=int, default=4)
    p.add_argument("--levels", type=int, default=4)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--impls", nargs="+",
                   default=["gather", "onehot", "pallas", "alt"])
    args = p.parse_args(argv)

    from raft_tpu.kernels import corr_lookup_pallas, pallas_available
    from raft_tpu.models.corr import (alt_corr_lookup, build_corr_pyramid,
                                      corr_lookup, corr_lookup_onehot)
    from raft_tpu.ops.pooling import avg_pool2x2

    B, (H, W), C = args.batch, args.hw, args.dim
    rng = np.random.RandomState(0)
    fmap1 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    fmap2 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    base = np.stack(np.meshgrid(np.arange(W), np.arange(H)), -1)
    coords = jnp.asarray(base[None].astype(np.float32)
                         + rng.randn(B, H, W, 2).astype(np.float32) * 4)

    pyramid = jax.block_until_ready(
        tuple(build_corr_pyramid(fmap1, fmap2, args.levels)))
    f2_pyr = [fmap2]
    for _ in range(args.levels - 1):
        f2_pyr.append(avg_pool2x2(f2_pyr[-1]))
    f2_pyr = jax.block_until_ready(tuple(f2_pyr))

    lookups = {
        "gather": jax.jit(lambda c: corr_lookup(pyramid, c, args.radius)),
        "onehot": jax.jit(
            lambda c: corr_lookup_onehot(pyramid, c, args.radius)),
        "pallas": jax.jit(
            lambda c: corr_lookup_pallas(pyramid, c, args.radius)),
        "alt": jax.jit(
            lambda c: alt_corr_lookup(fmap1, f2_pyr, c, args.radius)),
    }

    reference = None
    results = {}
    for name in args.impls:
        if name == "pallas" and not pallas_available():
            print(f"{name:>8}: skipped (no TPU backend)")
            continue
        try:
            dt, out = bench_fn(lookups[name], (coords,), iters=args.iters)
        except Exception as e:
            print(f"{name:>8}: FAILED {type(e).__name__}: {e}")
            continue
        out = np.asarray(out)
        if reference is None:
            reference = out
            diff = 0.0
        else:
            diff = float(np.abs(out - reference).max())
        results[name] = dt
        queries_per_s = B * H * W / dt
        print(f"{name:>8}: {dt * 1e3:8.3f} ms  "
              f"{queries_per_s / 1e6:8.2f} Mquery/s  max|Δ|={diff:.2e}")

    if results:
        fastest = min(results, key=results.get)
        print(f"fastest: {fastest}")
    return results


if __name__ == "__main__":
    main()
