"""Inference-throughput benchmark (the serving-side number).

The reference's only serving benchmark is ``test_trt.py:74-97`` — wall
clock around a PyTorch and a TensorRT forward with explicit synchronize
fences. This is that harness for the TPU serving path: the jitted
test-mode forward (what ``serving/engine.py`` buckets compile) timed with
the repo's honest remote-backend scheme (`utils/timing.py`): the iteration
loop runs inside ONE executable chained through an input nudge, weights
and images ride as jit arguments, and a single scalar fetch fences.

Run on the real chip:
    python -m raft_tpu.cli.infer_bench --hw 440 1024   # cvt2trt opt-ish
    python -m raft_tpu.cli.infer_bench --hw 368 496 --batch 8
"""

from __future__ import annotations

import argparse
import json

import numpy as np

import jax
import jax.numpy as jnp


def main(argv=None):
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser(description="serving forward throughput")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--hw", type=int, nargs=2, default=[440, 1024],
                   help="input H W (divisible by 8); default near the "
                        "cvt2trt.sh opt shape")
    p.add_argument("--iters", type=int, default=20,
                   help="refinement iterations (export bakes 20)")
    p.add_argument("--reps", type=int, default=10,
                   help="timed forwards inside the chained executable")
    p.add_argument("--small", action="store_true")
    from raft_tpu.cli._args import add_corr_args, corr_overrides

    add_corr_args(p)
    args = p.parse_args(argv)

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT
    from raft_tpu.utils.timing import chain_timed

    cfg = RAFTConfig(small=args.small, **corr_overrides(args))
    model = RAFT(cfg)
    H, W = args.hw
    rng = np.random.RandomState(0)
    img = jnp.asarray(rng.rand(args.batch, H, W, 3).astype(np.float32) * 255)
    # params are shape-independent: init tiny (the benchmark shape would
    # run hundreds of eager full-resolution dispatches over the tunnel)
    tiny = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), tiny, tiny, iters=1)

    def forward(image1, invars):
        variables, image2 = invars
        _, up = model.apply(variables, image1, image2, iters=args.iters,
                            test_mode=True)
        return up

    dt = chain_timed(forward, img, args.reps, (variables, img))
    pairs_per_s = args.batch / dt
    tag = "small" if args.small else "basic"
    suffix = "".join(
        f"_{v}" for v in (args.corr_impl,
                          f"corr{args.corr_dtype}" if args.corr_dtype
                          else None,
                          f"unroll{args.scan_unroll}"
                          if args.scan_unroll not in (None, 1)
                          else None) if v)
    print(json.dumps({
        "metric": f"raft_{tag}_infer_{H}x{W}_b{args.batch}"
                  f"_iters{args.iters}{suffix}",
        "value": round(pairs_per_s, 3),
        "unit": "img_pairs_per_sec",
        "ms_per_forward": round(dt * 1e3, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
