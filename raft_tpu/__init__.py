"""raft_tpu — a TPU-native (JAX/XLA/Pallas) optical-flow framework.

Re-designed from scratch with the capabilities of the LRLVEC/RAFT reference
(RAFT: Recurrent All-Pairs Field Transforms, ECCV 2020) but built TPU-first:

- NHWC layouts, bfloat16 mixed precision with fp32 correlation islands
- functional core: pure ``apply(params, batch)`` over pytrees
- ``lax.scan`` recurrent refinement, static shapes, jit-compiled end to end
- SPMD data/spatial parallelism via ``jax.sharding.Mesh`` + XLA collectives
- Pallas kernels for the correlation-lookup hot path
- AOT-compiled serving engine (the TensorRT-path analog)
"""

__version__ = "0.1.0"

from raft_tpu.config import RAFTConfig  # noqa: F401
