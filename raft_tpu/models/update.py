"""Recurrent update blocks: motion encoders, ConvGRUs, flow/mask heads.

Equivalents of ``/root/reference/core/update.py`` (NHWC, flax). Channel
arithmetic is the parity surface: basic corr feature 4·(2·4+1)²=324, motion
feature 126+2=128, GRU input 128+128 (update.py:82,87,97,119); small corr
feature 4·49=196, motion 80+2=82, GRU input 82+64 (update.py:65,103).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from raft_tpu.models.layers import (TorchConv, conv_lane_major,
                                    conv_pair_lane_major, fused_conv_pair)


class FlowHead(nn.Module):
    """2-layer conv head -> delta flow (update.py:6-14)."""

    hidden_dim: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = TorchConv(self.hidden_dim, (3, 3), (1, 1), (1, 1), self.dtype,
                      name="conv1")(x)
        x = nn.relu(x)
        return TorchConv(2, (3, 3), (1, 1), (1, 1), self.dtype,
                         name="conv2")(x)


class ConvGRU(nn.Module):
    """Full 3x3 ConvGRU (update.py:16-31). h, x: NHWC."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h, x):
        hx = jnp.concatenate([h, x], axis=-1)
        # z and r read the same hx: one double-width conv (identical
        # values, params stay separate — see fused_conv_pair)
        zl, rl = fused_conv_pair(
            TorchConv(self.hidden_dim, (3, 3), (1, 1), (1, 1),
                      self.dtype, name="convz"),
            TorchConv(self.hidden_dim, (3, 3), (1, 1), (1, 1),
                      self.dtype, name="convr"), hx)
        z, r = nn.sigmoid(zl), nn.sigmoid(rl)
        q = nn.tanh(TorchConv(self.hidden_dim, (3, 3), (1, 1), (1, 1),
                              self.dtype, name="convq")(
            jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Separable 1x5 + 5x1 ConvGRU (update.py:33-60)."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h, x):
        # z/r of each direction share their input hx: run each pair as
        # one double-width conv (identical values, see fused_conv_pair)
        # horizontal (1x5)
        hx = jnp.concatenate([h, x], axis=-1)
        zl, rl = fused_conv_pair(
            TorchConv(self.hidden_dim, (1, 5), (1, 1), (0, 2),
                      self.dtype, name="convz1"),
            TorchConv(self.hidden_dim, (1, 5), (1, 1), (0, 2),
                      self.dtype, name="convr1"), hx)
        z, r = nn.sigmoid(zl), nn.sigmoid(rl)
        q = nn.tanh(TorchConv(self.hidden_dim, (1, 5), (1, 1), (0, 2),
                              self.dtype, name="convq1")(
            jnp.concatenate([r * h, x], axis=-1)))
        h = (1 - z) * h + z * q

        # vertical (5x1)
        hx = jnp.concatenate([h, x], axis=-1)
        zl, rl = fused_conv_pair(
            TorchConv(self.hidden_dim, (5, 1), (1, 1), (2, 0),
                      self.dtype, name="convz2"),
            TorchConv(self.hidden_dim, (5, 1), (1, 1), (2, 0),
                      self.dtype, name="convr2"), hx)
        z, r = nn.sigmoid(zl), nn.sigmoid(rl)
        q = nn.tanh(TorchConv(self.hidden_dim, (5, 1), (1, 1), (2, 0),
                              self.dtype, name="convq2")(
            jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q


class SmallMotionEncoder(nn.Module):
    """corr+flow -> 80+2 ch motion features (update.py:62-77)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr):
        cor = nn.relu(TorchConv(96, (1, 1), (1, 1), (0, 0), self.dtype,
                                name="convc1")(corr))
        flo = nn.relu(TorchConv(64, (7, 7), (1, 1), (3, 3), self.dtype,
                                name="convf1")(flow))
        flo = nn.relu(TorchConv(32, (3, 3), (1, 1), (1, 1), self.dtype,
                                name="convf2")(flo))
        out = nn.relu(TorchConv(80, (3, 3), (1, 1), (1, 1), self.dtype,
                                name="conv")(jnp.concatenate([cor, flo], -1)))
        return jnp.concatenate([out, flow.astype(out.dtype)], axis=-1)


class BasicMotionEncoder(nn.Module):
    """corr+flow -> 126+2 ch motion features (update.py:79-97)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr):
        cor = nn.relu(TorchConv(256, (1, 1), (1, 1), (0, 0), self.dtype,
                                name="convc1")(corr))
        cor = nn.relu(TorchConv(192, (3, 3), (1, 1), (1, 1), self.dtype,
                                name="convc2")(cor))
        flo = nn.relu(TorchConv(128, (7, 7), (1, 1), (3, 3), self.dtype,
                                name="convf1")(flow))
        flo = nn.relu(TorchConv(64, (3, 3), (1, 1), (1, 1), self.dtype,
                                name="convf2")(flo))
        out = nn.relu(TorchConv(126, (3, 3), (1, 1), (1, 1), self.dtype,
                                name="conv")(jnp.concatenate([cor, flo], -1)))
        return jnp.concatenate([out, flow.astype(out.dtype)], axis=-1)


class FusedSepConvGRU(nn.Module):
    """Lane-major SepConvGRU (``gru_impl='fused'``): same parameters and
    fp32 math as :class:`SepConvGRU`, restructured for the TPU.

    ``h``/``x`` arrive flattened ``(B, H·W, C)``; the 1x5/5x1 convs run
    as per-tap shifted GEMM accumulations in that layout (see
    ``layers._apply_conv_lane_major`` — the 46x62 spatial plane rides
    sublanes instead of fragmenting into tile-padded small convs), the
    z/r pair of each direction shares one double-width tap contraction,
    and the elementwise gate/blend tails run in the fused Pallas
    epilogues (``kernels.gru_pallas``) so z, r, r·h and tanh(q) never
    round-trip HBM between conv fusions inside the 12-iteration scan.
    """

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h, x, hw):
        from raft_tpu.kernels.gru_pallas import gru_cell_lane_major

        dirs = (
            # (kernel, padding, z-name, r-name, q-name)
            ((1, 5), (0, 2), "convz1", "convr1", "convq1"),  # horizontal
            ((5, 1), (2, 0), "convz2", "convr2", "convq2"),  # vertical
        )
        for k, pad, zn, rn, qn in dirs:
            hx = jnp.concatenate([h, x], axis=-1)
            zl, rl = conv_pair_lane_major(
                TorchConv(self.hidden_dim, k, (1, 1), pad, self.dtype,
                          name=zn),
                TorchConv(self.hidden_dim, k, (1, 1), pad, self.dtype,
                          name=rn), hx, hw)
            convq = TorchConv(self.hidden_dim, k, (1, 1), pad, self.dtype,
                              name=qn)
            h = gru_cell_lane_major(
                h, zl, rl,
                lambda rh, convq=convq: conv_lane_major(
                    convq, jnp.concatenate([rh, x], axis=-1), hw))
        return h


class FusedBasicMotionEncoder(nn.Module):
    """Lane-major :class:`BasicMotionEncoder`: identical parameters and
    channel arithmetic (126+2), convs as shifted tap contractions. The
    7x7-on-flow conv has cin=2, so its taps stay broadcast FMAs
    (``layers._FMA_MAX_CIN``) rather than padding a 2-deep contraction
    onto the MXU."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr, hw):
        cor = nn.relu(conv_lane_major(
            TorchConv(256, (1, 1), (1, 1), (0, 0), self.dtype,
                      name="convc1"), corr, hw))
        cor = nn.relu(conv_lane_major(
            TorchConv(192, (3, 3), (1, 1), (1, 1), self.dtype,
                      name="convc2"), cor, hw))
        flo = nn.relu(conv_lane_major(
            TorchConv(128, (7, 7), (1, 1), (3, 3), self.dtype,
                      name="convf1"), flow, hw))
        flo = nn.relu(conv_lane_major(
            TorchConv(64, (3, 3), (1, 1), (1, 1), self.dtype,
                      name="convf2"), flo, hw))
        out = nn.relu(conv_lane_major(
            TorchConv(126, (3, 3), (1, 1), (1, 1), self.dtype,
                      name="conv"), jnp.concatenate([cor, flo], -1), hw))
        return jnp.concatenate([out, flow.astype(out.dtype)], axis=-1)


class FusedBasicUpdateBlock(nn.Module):
    """``gru_impl='fused'`` drop-in for :class:`BasicUpdateBlock`: same
    parameter tree (checkpoints interchangeable, oracle-pinned in
    tests/test_gru_fused.py), NHWC at the interface, lane-major inside.

    The motion encoder and GRU — the scan body's latency-bound band —
    run flattened; the flow head and mask head stay NHWC: they run once
    per iteration on 128→256-channel 3x3 convs that are already
    MXU-shaped, and the batched convex upsampler consumes their NHWC
    outputs directly after the scan.
    """

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, net, inp, corr, flow):
        B, H, W, _ = net.shape
        hw = (H, W)

        def flat(a):
            return a.reshape(B, H * W, a.shape[-1])

        motion = FusedBasicMotionEncoder(self.dtype, name="encoder")(
            flat(flow), flat(corr), hw)
        gru_in = jnp.concatenate([flat(inp), motion], axis=-1)
        net_f = FusedSepConvGRU(self.hidden_dim, self.dtype, name="gru")(
            flat(net), gru_in, hw)
        net = net_f.reshape(B, H, W, self.hidden_dim)
        delta = FlowHead(256, self.dtype, name="flow_head")(net)

        # .25 scale to balance gradients (update.py:134-135)
        mask = TorchConv(256, (3, 3), (1, 1), (1, 1), self.dtype,
                         name="mask_conv1")(net)
        mask = nn.relu(mask)
        mask = TorchConv(64 * 9, (1, 1), (1, 1), (0, 0), self.dtype,
                         name="mask_conv2")(mask)
        return net, 0.25 * mask, delta


class SmallUpdateBlock(nn.Module):
    """Motion encoder + ConvGRU + flow head; no mask head (update.py:99-112)."""

    hidden_dim: int = 96
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, net, inp, corr, flow):
        motion = SmallMotionEncoder(self.dtype, name="encoder")(flow, corr)
        gru_in = jnp.concatenate([inp, motion], axis=-1)
        net = ConvGRU(self.hidden_dim, self.dtype, name="gru")(net, gru_in)
        delta = FlowHead(128, self.dtype, name="flow_head")(net)
        return net, None, delta


class BasicUpdateBlock(nn.Module):
    """Motion encoder + SepConvGRU + flow head + mask head (update.py:114-136)."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, net, inp, corr, flow):
        motion = BasicMotionEncoder(self.dtype, name="encoder")(flow, corr)
        gru_in = jnp.concatenate([inp, motion], axis=-1)
        net = SepConvGRU(self.hidden_dim, self.dtype, name="gru")(net, gru_in)
        delta = FlowHead(256, self.dtype, name="flow_head")(net)

        # .25 scale to balance gradients (update.py:134-135)
        mask = TorchConv(256, (3, 3), (1, 1), (1, 1), self.dtype,
                         name="mask_conv1")(net)
        mask = nn.relu(mask)
        mask = TorchConv(64 * 9, (1, 1), (1, 1), (0, 0), self.dtype,
                         name="mask_conv2")(mask)
        return net, 0.25 * mask, delta
