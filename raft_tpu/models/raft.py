"""RAFT model: encoders + correlation + scanned recurrent refinement.

TPU-native re-design of ``/root/reference/core/raft.py``. Differences from
the reference that are deliberate:

- The refinement loop is a ``flax.linen.scan`` (= ``lax.scan``) over the
  update block — one compiled iteration body instead of an unrolled graph,
  with ``stop_gradient`` on the coordinate chain replicating the
  per-iteration ``coords1.detach()`` autograd structure (core/raft.py:123).
- NHWC layout; both images run through the feature net as one doubled batch
  (core/extractor.py:171-174) to keep MXU GEMMs large.
- ``test_mode`` returns BOTH the low-res flow and the upsampled flow,
  restoring upstream semantics (the fork's single-output return at
  core/raft.py:141-143 breaks its own eval callers — see SURVEY.md).
- fp32 islands under mixed precision: fmaps are cast to fp32 before
  correlation (core/raft.py:102-103); lookups and convex upsampling run fp32.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models.corr import (
    AlternateCorrBlock,
    CorrBlock,
    alt_corr_lookup,
    build_corr_pyramid,
    build_corr_pyramid_t,
    corr_lookup,
    corr_lookup_onehot,
    corr_lookup_onehot_t,
    corr_lookup_softsel,
    corr_lookup_softsel_t,
)
from raft_tpu.models.encoders import BasicEncoder, SmallEncoder
from raft_tpu.models.update import (BasicUpdateBlock, FusedBasicUpdateBlock,
                                    SmallUpdateBlock)
from raft_tpu.ops.flow_ops import (
    convex_upsample_batched,
    convex_upsample_batched_raw,
    initialize_flow,
    upflow8_batched,
)
from raft_tpu.ops.pooling import avg_pool2x2


class RAFT(nn.Module):
    """Recurrent All-Pairs Field Transforms (core/raft.py:24)."""

    config: RAFTConfig = RAFTConfig()

    def setup(self):
        cfg = self.config
        dt = cfg.compute_dtype
        if cfg.small:
            self.fnet = SmallEncoder(cfg.fnet_dim, cfg.fnet_norm, cfg.dropout,
                                     dt)
            self.cnet = SmallEncoder(cfg.cnet_dim, cfg.cnet_norm, cfg.dropout,
                                     dt)
            self.update_block = SmallUpdateBlock(cfg.hidden_dim, dt)
        else:
            self.fnet = BasicEncoder(cfg.fnet_dim, cfg.fnet_norm, cfg.dropout,
                                     dt)
            self.cnet = BasicEncoder(cfg.cnet_dim, cfg.cnet_norm, cfg.dropout,
                                     dt)
            # gru_impl selects the scan-body implementation, never the
            # parameters: both blocks declare the identical tree, so
            # checkpoints and the whole-step A/B rungs swap freely
            # (mirrors the corr_impl pattern; see RAFTConfig.gru_impl)
            if cfg.gru_impl == "fused":
                self.update_block = FusedBasicUpdateBlock(cfg.hidden_dim, dt)
            else:
                self.update_block = BasicUpdateBlock(cfg.hidden_dim, dt)

    def _corr_setup(self, fmap1, fmap2):
        """Correlation state + per-iteration lookup fn for an fp32 fmap
        pair — the ``corr_impl`` dispatch, shared by ``__call__`` and
        the cross-frame cached serving path (``forward_cached``), so
        the two can never drift."""
        cfg = self.config
        if cfg.alternate_corr:
            pyr = [fmap2]
            f2 = fmap2
            for _ in range(cfg.corr_levels - 1):
                f2 = avg_pool2x2(f2)
                pyr.append(f2)
            if cfg.corr_impl == "pallas":
                from raft_tpu.kernels.corr_alt_pallas import (
                    alt_corr_lookup_pallas, pad_f2_pyramid)

                # pad once, outside the scanned loop (loop-invariant)
                corr_state = (fmap1,
                              pad_f2_pyramid(pyr, cfg.corr_radius))

                def lookup(state, coords):
                    f1, f2_pyr = state
                    return alt_corr_lookup_pallas(
                        f1, f2_pyr, coords, cfg.corr_radius, prepadded=True)
            else:
                corr_state = (fmap1, tuple(pyr))

                def lookup(state, coords):
                    f1, f2_pyr = state
                    return alt_corr_lookup(f1, f2_pyr, coords,
                                           cfg.corr_radius)
        elif cfg.corr_impl in ("onehot_t", "softsel_t"):
            # transposed (pixels-on-lanes) volume — see build_corr_pyramid_t
            corr_state = tuple(
                v.astype(cfg.corr_dtype)
                for v in build_corr_pyramid_t(fmap1, fmap2, cfg.corr_levels))
            lookup_t = (corr_lookup_softsel_t
                        if cfg.corr_impl == "softsel_t"
                        else corr_lookup_onehot_t)

            def lookup(state, coords):
                return lookup_t(state, coords, cfg.corr_radius)
        else:
            corr_state = tuple(
                v.astype(cfg.corr_dtype)
                for v in build_corr_pyramid(fmap1, fmap2, cfg.corr_levels))
            if cfg.corr_impl == "pallas":
                from raft_tpu.kernels import corr_lookup_pallas, pad_pyramid

                # pad once, outside the scanned loop (the pyramid is
                # nn.broadcast — loop-invariant)
                corr_state = pad_pyramid(corr_state, cfg.corr_radius)

                def lookup(state, coords):
                    return corr_lookup_pallas(state, coords, cfg.corr_radius,
                                              prepadded=True)
            else:
                lookup_fn = {"onehot": corr_lookup_onehot,
                             "softsel": corr_lookup_softsel,
                             "gather": corr_lookup}[cfg.corr_impl]

                def lookup(state, coords):
                    return lookup_fn(state, coords, cfg.corr_radius)
        return corr_state, lookup

    def _refine(self, corr_state, lookup, net, inp, B, H, W,
                iters: int, flow_init, test_mode: bool,
                raw_predictions: bool = False):
        """The scanned refinement recurrence + upsampling tail, from
        initialized flow coordinates to the mode's return values —
        shared verbatim by ``__call__`` and ``forward_cached``."""
        cfg = self.config
        dt = cfg.compute_dtype
        coords0, coords1 = initialize_flow(B, H // 8, W // 8)
        if flow_init is not None:
            coords1 = coords1 + flow_init

        small = cfg.small

        # Upsampling happens OUTSIDE the scan, batched over all iterations:
        # the per-iteration convex upsample materializes (B,H,W,9,8,8)-shaped
        # tensors whose minor dims waste ~94% of the TPU (8,128) memory tile
        # — measured at ~35% of the whole train step (see
        # ops/flow_ops.convex_upsample_batched). In train mode the scan
        # emits the low-res flow (+ mask) per iteration — a smaller stack
        # than full-res predictions (576 bf16 channels at H/8 vs 2 fp32 at
        # H). In test mode only the LAST iteration is upsampled, and the
        # final mask rides the carry so nothing is stacked at all.
        def _iteration(update_block, carry, inp, coords0, corr_state):
            net, coords1 = carry[0], carry[1]
            coords1 = jax.lax.stop_gradient(coords1)  # core/raft.py:123
            corr = lookup(corr_state, coords1)
            flow = coords1 - coords0
            net, up_mask, delta = update_block(
                net, inp, corr.astype(dt), flow.astype(dt))
            coords1 = coords1 + delta.astype(jnp.float32)
            if test_mode:
                carry = ((net, coords1) if small
                         else (net, coords1, up_mask))
                return carry, None
            new_flow = coords1 - coords0
            ys = new_flow if small else (new_flow, up_mask)
            return (net, coords1), ys

        if cfg.remat:
            policy = (jax.checkpoint_policies.checkpoint_dots
                      if cfg.remat_policy == "dots" else None)
            body = nn.remat(_iteration, policy=policy)
        else:
            body = _iteration
        scan = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": False},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            out_axes=0,
            length=iters,
            unroll=cfg.scan_unroll,
        )
        init_carry = (net, coords1)
        if test_mode and not small:
            init_carry = (net, coords1,
                          jnp.zeros((B, H // 8, W // 8, 64 * 9), dt))
        carry, ys = scan(
            self.update_block, init_carry, inp, coords0, corr_state)
        coords1 = carry[1]
        flow_lr = coords1 - coords0

        if test_mode:
            if small:
                flow_up = upflow8_batched(flow_lr[None])[0]
            else:
                flow_up = convex_upsample_batched(flow_lr[None],
                                                  carry[2][None])[0]
            return flow_lr, flow_up

        if small:
            assert not raw_predictions, (
                "raw_predictions applies to the learned convex upsampler; "
                "the small model upsamples bilinearly")
            flow_predictions = upflow8_batched(ys)
        elif raw_predictions:
            flow_predictions = convex_upsample_batched_raw(*ys)
        else:
            flow_predictions = convex_upsample_batched(*ys)
        return flow_predictions

    def __call__(self, image1, image2, iters: int = 12,
                 flow_init: Optional[jax.Array] = None,
                 test_mode: bool = False, train: bool = False,
                 freeze_bn: bool = False, raw_predictions: bool = False):
        """Estimate flow. Images: (B, H, W, 3) float in [0, 255], H, W % 8 == 0.

        Returns all per-iteration upsampled flows (iters, B, H, W, 2) in
        train mode, or ``(flow_low, flow_up)`` in test mode. With
        ``raw_predictions=True`` (basic model, train mode) the stack comes
        back in the upsampler's subpixel domain (iters, B, 2, 64, H/8·W/8 —
        see ops/flow_ops.convex_upsample_batched_raw) for the fused
        sequence loss; the full-res stack never materializes.
        """
        cfg = self.config
        dt = cfg.compute_dtype
        B, H, W, _ = image1.shape
        assert H % 8 == 0 and W % 8 == 0, "pad inputs with InputPadder first"
        ura = (not train) or freeze_bn  # BatchNorm running-average switch

        # normalize to [-1, 1] (core/raft.py:89-90)
        image1 = 2.0 * (image1.astype(jnp.float32) / 255.0) - 1.0
        image2 = 2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0

        if cfg.split_encode:
            # two fnet calls (shared parameters): under a batch-sharded
            # mesh the reference's concat trick below redistributes every
            # row (see RAFTConfig.split_encode); instance norm makes the
            # split exact per sample
            fmap1 = self.fnet(image1, train=train,
                              use_running_average=ura).astype(jnp.float32)
            fmap2 = self.fnet(image2, train=train,
                              use_running_average=ura).astype(jnp.float32)
        else:
            # feature network over both images as one batch
            fmaps = self.fnet(jnp.concatenate([image1, image2], axis=0),
                              train=train, use_running_average=ura)
            fmap1 = fmaps[:B].astype(jnp.float32)   # fp32 island for
            fmap2 = fmaps[B:].astype(jnp.float32)   # correlation

        corr_state, lookup = self._corr_setup(fmap1, fmap2)

        # context network (core/raft.py:110-114)
        cnet = self.cnet(image1, train=train, use_running_average=ura)
        net = jnp.tanh(cnet[..., :cfg.hidden_dim]).astype(dt)
        inp = nn.relu(cnet[..., cfg.hidden_dim:]).astype(dt)

        return self._refine(corr_state, lookup, net, inp, B, H, W,
                            iters, flow_init, test_mode, raw_predictions)

    def forward_ragged(self, image1, image2, valid_h8, valid_w8,
                       flow_init: Optional[jax.Array] = None,
                       iters: int = 12):
        """Ragged serving: ONE program for mixed spatial shapes.

        A ragged micro-batch packs requests of different ``(h, w)``
        into one ``(B, Hcap, Wcap)`` capacity box (each row edge-padded
        to its own ÷8 alignment, then zero-filled); ``valid_h8`` /
        ``valid_w8`` are (B,) int32 per-row valid extents at 1/8
        resolution — the ragged descriptor of arXiv 2604.15464, carried
        as TRACED arguments so every extent mix runs the same compiled
        program. The encoders run over the whole box (convolutions need
        the box's spatial structure); the correlation path then applies
        masked-tail semantics (kernels/corr_ragged_pallas): features
        past each row's valid extent are zeroed, so a row's correlation
        volume is exactly its own smaller volume zero-embedded in the
        box, and every lookup backend's zeros-outside semantics makes
        the per-iteration window gather ragged for free — the
        descriptor rides the scanned refinement loop inside the masked
        ``corr_state`` the GRU body's lookup closes over.

        Returns the test-mode ``(flow_low, flow_up)`` pair at the box
        geometry; the serving layer crops each row to its request.

        Bitwise note: a FULL-extent row (valid extents == the box) is
        masked by an all-true select — exact identity — so its outputs
        are bitwise what ``__call__`` computes on the same padded batch
        (the ragged-vs-bucketed oracle pin, tests/test_ragged.py). A
        sub-capacity row instead gets the masked zeros-tail semantics:
        cleaner than the bucketed path's fill-feature correlations, but
        a different program than exact-shape compilation — the box
        fill still shifts the encoders' instance-norm statistics
        exactly as bucket fill does (see ``RAFTEngine.infer_batch``'s
        accuracy note).
        """
        cfg = self.config
        dt = cfg.compute_dtype
        B, H, W, _ = image1.shape
        assert H % 8 == 0 and W % 8 == 0, "capacity boxes are ÷8-aligned"

        image1 = 2.0 * (image1.astype(jnp.float32) / 255.0) - 1.0
        image2 = 2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0

        fmaps = self.fnet(jnp.concatenate([image1, image2], axis=0),
                          train=False, use_running_average=True)
        from raft_tpu.kernels.corr_ragged_pallas import mask_features

        fmap1 = mask_features(fmaps[:B].astype(jnp.float32),
                              valid_h8, valid_w8)
        fmap2 = mask_features(fmaps[B:].astype(jnp.float32),
                              valid_h8, valid_w8)

        corr_state, lookup = self._corr_setup(fmap1, fmap2)

        cnet = self.cnet(image1, train=False, use_running_average=True)
        net = jnp.tanh(cnet[..., :cfg.hidden_dim]).astype(dt)
        inp = nn.relu(cnet[..., cfg.hidden_dim:]).astype(dt)

        return self._refine(corr_state, lookup, net, inp, B, H, W,
                            iters, flow_init, True)

    def forward_cached(self, image2, fmap1, cnet1,
                       flow_init: jax.Array, iters: int = 12):
        """Cross-frame cached serving: encode ONLY the new frame.

        For consecutive video pairs the previous dispatch already
        encoded this pair's first frame — frame t's ``fmap2`` and a
        speculative context encoding ARE pair (t, t+1)'s
        ``fmap1``/context inputs — so per-stream device caches
        (serving/feature_cache) hand them back instead of re-running
        the encoders: steady-state video costs one encoder pass + one
        recurrence per frame instead of two (the compiler-first O(1)
        autoregressive-cache discipline of arXiv 2603.09555, applied
        to RAFT's encoder state).

        ``image2``: (B, H, W, 3) float in [0, 255] — the NEW frame
        only; the pair's first frame never ships. ``fmap1``: (B, H/8,
        W/8, fnet_dim) fp32 — the previous call's ``fmap2`` output.
        ``cnet1``: (B, H/8, W/8, cnet_dim) fp32 — the previous call's
        speculative context (raw ``cnet`` output; the tanh/relu split
        happens here, on bits identical to what ``__call__`` would
        see — fp32 storage round-trips any compute dtype losslessly).
        ``flow_init``: (B, H/8, W/8, 2) recurrence warm start (zeros =
        cold recurrence).

        Returns ``(flow_low, flow_up, fmap2, cnet2)`` — the test-mode
        pair plus this frame's cache outputs (both fp32). A ZEROED
        fmap1/cnet1 row is the PRIME form of a cold start: its flow
        outputs are refinement against zero features (meaningless, and
        the serving layer never surfaces them) but its cache outputs
        are exactly this frame's features — the next pair's warm
        inputs — which is how cold and warm stream rows coalesce into
        ONE bucket executable.

        Bitwise note: the feature net runs at batch B here vs 2B in
        ``__call__``; XLA CPU conv bits move with TOTAL batch size
        once it exceeds the vectorization width (batch 1 == 2,
        2 != 4 — pinned in tests/test_feature_cache.py), so the
        bitwise cached-vs-uncached pin holds at the bucket-batch-1
        serving geometry and is allclose-tight above it.
        """
        cfg = self.config
        dt = cfg.compute_dtype
        B, H, W, _ = image2.shape
        assert H % 8 == 0 and W % 8 == 0, "pad inputs with InputPadder first"

        image2 = 2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0
        fmap2 = self.fnet(image2, train=False,
                          use_running_average=True).astype(jnp.float32)
        # speculative context for the NEXT pair (this frame will be its
        # frame 1) — the one extra encoder pass that makes the stream
        # self-sustaining
        cnet2 = self.cnet(image2, train=False, use_running_average=True)

        corr_state, lookup = self._corr_setup(
            fmap1.astype(jnp.float32), fmap2)

        # cached context: cast back to the encoder's own dtype so the
        # tanh/relu split sees the exact bits __call__ would (fp32
        # caching of a bf16 value is a lossless round trip)
        cnet1 = cnet1.astype(cnet2.dtype)
        net = jnp.tanh(cnet1[..., :cfg.hidden_dim]).astype(dt)
        inp = nn.relu(cnet1[..., cfg.hidden_dim:]).astype(dt)

        flow_low, flow_up = self._refine(
            corr_state, lookup, net, inp, B, H, W, iters, flow_init,
            True)
        return flow_low, flow_up, fmap2, cnet2.astype(jnp.float32)


def create_raft(config: RAFTConfig = RAFTConfig()):
    return RAFT(config)
