"""Feature/context encoders (extractor.py:118-267), NHWC flax.

3-stage residual CNNs with total stride 8. The two input images are batched
through one conv pass (``extractor.py:171-174``) by the caller concatenating
on the batch dim — on TPU this doubles the effective GEMM batch for the MXU.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from raft_tpu.models.layers import (
    BottleneckBlock,
    Norm,
    ResidualBlock,
    TorchConv,
    conv1x1,
)


class BasicEncoder(nn.Module):
    """64 -> 64 -> 96 -> 128 residual encoder + 1x1 head (extractor.py:118)."""

    output_dim: int = 128
    norm_fn: str = "batch"
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, use_running_average: bool = True):
        ura = use_running_average
        x = TorchConv(64, (7, 7), (2, 2), (3, 3), self.dtype, name="conv1")(x)
        # stem GroupNorm uses 8 groups, not 64//8 (extractor.py:124)
        x = Norm(self.norm_fn, 64, num_groups=8, name="norm1")(x, ura)
        x = nn.relu(x)

        for i, (dim, stride) in enumerate([(64, 1), (96, 2), (128, 2)], 1):
            x = ResidualBlock(dim, self.norm_fn, stride, self.dtype,
                              name=f"layer{i}_0")(x, ura)
            x = ResidualBlock(dim, self.norm_fn, 1, self.dtype,
                              name=f"layer{i}_1")(x, ura)

        x = conv1x1(self.output_dim, 1, self.dtype, name="conv2")(x)

        if self.dropout > 0:
            # torch Dropout2d drops whole channels (extractor.py:146-148)
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2),
                           deterministic=not train)(x)
        return x


class SmallEncoder(nn.Module):
    """32 -> 32 -> 64 -> 96 bottleneck encoder + 1x1 head (extractor.py:195)."""

    output_dim: int = 128
    norm_fn: str = "batch"
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, use_running_average: bool = True):
        ura = use_running_average
        x = TorchConv(32, (7, 7), (2, 2), (3, 3), self.dtype, name="conv1")(x)
        x = Norm(self.norm_fn, 32, num_groups=8, name="norm1")(x, ura)
        x = nn.relu(x)

        for i, (dim, stride) in enumerate([(32, 1), (64, 2), (96, 2)], 1):
            x = BottleneckBlock(dim, self.norm_fn, stride, self.dtype,
                                name=f"layer{i}_0")(x, ura)
            x = BottleneckBlock(dim, self.norm_fn, 1, self.dtype,
                                name=f"layer{i}_1")(x, ura)

        x = conv1x1(self.output_dim, 1, self.dtype, name="conv2")(x)

        if self.dropout > 0:
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2),
                           deterministic=not train)(x)
        return x
