"""Correlation volume: all-pairs pyramid + windowed lookup (NHWC, TPU-first).

Replaces ``core/corr.py`` and the CUDA ``alt_cuda_corr`` extension
(``alt_cuda_corr/correlation_kernel.cu``). Two paths, same output layout:

- ``CorrBlock``: materialize the (H1·W1)×(H2·W2) volume with ONE big MXU
  GEMM (``corr.py:52-60``), average-pool a 4-level pyramid (``corr.py:25-27``),
  and per iteration gather a (2r+1)² window around the current coords
  (``corr.py:29-50``) via flattened-index 4-corner gathers.
- ``AlternateCorrBlock``: never materialize the volume; per iteration
  bilinearly sample fmap2 at the window points and dot with fmap1
  (O(HW·(2r+1)²·levels) memory). Since correlation is linear in fmap2,
  interpolate-then-dot ≡ sampling the true corr volume — exactly what the
  CUDA kernel computes with its scatter form (correlation_kernel.cu:19-119).

Output channel layout (the checkpoint parity surface): c = level·K² +
x_idx·K + y_idx with K = 2r+1 — the x-offset enumerates the OUTER index.
This mirrors both reference paths: ``corr.py:39-43`` adds the meshgrid's
``dy`` to the x coordinate, and the CUDA kernel scatters to channel
``(iy-1) + rd*(ix-1)`` (correlation_kernel.cu:92-95) — i.e. x-major.
"""

from __future__ import annotations

import math
from typing import List, Sequence

import jax
import jax.numpy as jnp

from raft_tpu.ops.pooling import avg_pool2x2

HIGHEST = jax.lax.Precision.HIGHEST


def all_pairs_correlation(fmap1: jax.Array, fmap2: jax.Array) -> jax.Array:
    """(B,H,W,C) x2 -> (B, H*W, H, W) all-pairs dot products / sqrt(C).

    Equivalent of ``CorrBlock.corr`` (corr.py:52-60). fp32 island: the
    reference casts fmaps to fp32 before correlation regardless of autocast
    (core/raft.py:102-103); precision=HIGHEST keeps the MXU in fp32-accurate
    mode for it.
    """
    B, H, W, C = fmap1.shape
    f1 = fmap1.astype(jnp.float32).reshape(B, H * W, C)
    f2 = fmap2.astype(jnp.float32).reshape(B, H * W, C)
    corr = jnp.einsum("bxc,byc->bxy", f1, f2, precision=HIGHEST)
    corr = corr / math.sqrt(C)
    return corr.reshape(B, H * W, H, W)


def build_corr_pyramid(fmap1: jax.Array, fmap2: jax.Array,
                       num_levels: int = 4) -> List[jax.Array]:
    """List of (B, N, Hl, Wl) volumes, level 0 full res (corr.py:18-27)."""
    corr = all_pairs_correlation(fmap1, fmap2)
    pyramid = [corr]
    for _ in range(num_levels - 1):
        c = avg_pool2x2(corr[..., None])[..., 0]
        pyramid.append(c)
        corr = c
    return pyramid


def _window_offsets(radius: int):
    """(K², ) x/y offsets, x-major channel order (see module docstring)."""
    d = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    K = 2 * radius + 1
    du = jnp.repeat(d, K)   # x offset: outer index
    dv = jnp.tile(d, K)     # y offset: inner index
    return du, dv


def _gather_bilinear_2d(vol: jax.Array, xs: jax.Array, ys: jax.Array) -> jax.Array:
    """Bilinear-sample ``vol`` (B, N, H, W) at per-(B,N) points (B, N, P).

    zeros out-of-bounds (grid_sample padding_mode='zeros' semantics).
    Returns (B, N, P). Implemented as 4 flattened-index gathers so XLA emits
    batched dynamic-gathers instead of scatter/gather soup.
    """
    B, N, H, W = vol.shape
    flat = vol.reshape(B, N, H * W)

    x0 = jnp.floor(xs)
    y0 = jnp.floor(ys)
    wx = xs - x0
    wy = ys - y0

    def corner(xi, yi, w):
        valid = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xi_c = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        idx = yi_c * W + xi_c
        vals = jnp.take_along_axis(flat, idx, axis=2)
        return vals * (w * valid.astype(jnp.float32))

    return (
        corner(x0, y0, (1 - wx) * (1 - wy))
        + corner(x0 + 1, y0, wx * (1 - wy))
        + corner(x0, y0 + 1, (1 - wx) * wy)
        + corner(x0 + 1, y0 + 1, wx * wy)
    )


def corr_lookup(pyramid: Sequence[jax.Array], coords: jax.Array,
                radius: int) -> jax.Array:
    """Sample (2r+1)² windows at every level around ``coords`` (B,H,W,2).

    Returns (B, H, W, num_levels*K²) fp32 — the per-iteration correlation
    features (corr.py:29-50).
    """
    B, H, W, _ = coords.shape
    N = H * W
    du, dv = _window_offsets(radius)

    x = coords[..., 0].reshape(B, N, 1).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N, 1).astype(jnp.float32)

    out = []
    for i, vol in enumerate(pyramid):
        xs = x / (2 ** i) + du[None, None, :]
        ys = y / (2 ** i) + dv[None, None, :]
        out.append(_gather_bilinear_2d(vol, xs, ys))
    return jnp.concatenate(out, axis=-1).reshape(B, H, W, -1)


def _window_base(x: jax.Array, y: jax.Array, radius: int):
    """Integer window base + shared bilinear fracs for a (K+1)² window.

    All taps ``x+du`` share ``frac(x)`` since ``du`` is integer, so the
    (2r+1)² bilinear lookup decomposes into an integer (2r+2)² window fetch
    followed by a separable 2-tap lerp — the structure both the one-hot and
    Pallas paths exploit (and exactly what the CUDA kernel's (2r+2)² iteration
    space is, correlation_kernel.cu:56-99).
    """
    xf = jnp.floor(x)
    yf = jnp.floor(y)
    x0 = xf.astype(jnp.int32) - radius
    y0 = yf.astype(jnp.int32) - radius
    return x0, y0, x - xf, y - yf


def _separable_lerp(win: jax.Array, wx: jax.Array, wy: jax.Array,
                    radius: int) -> jax.Array:
    """(..., K+1, K+1) [y, x] window -> (..., K²) x-major channel layout."""
    K = 2 * radius + 1
    wy_ = wy[..., None, None]
    wx_ = wx[..., None, None]
    wl = (1.0 - wy_) * win[..., :K, :] + wy_ * win[..., 1:, :]
    out = (1.0 - wx_) * wl[..., :, :K] + wx_ * wl[..., :, 1:]
    # [y, x] -> x-major flat (module docstring channel layout)
    return jnp.swapaxes(out, -1, -2).reshape(*out.shape[:-2], K * K)


def corr_lookup_onehot(pyramid: Sequence[jax.Array], coords: jax.Array,
                       radius: int) -> jax.Array:
    """MXU-native lookup: one-hot row/col selection instead of gathers.

    Gathers are the TPU's weak spot (SURVEY.md §7 hard part #1); selecting
    the (2r+2)² integer window with two one-hot einsums turns the lookup
    into batched GEMMs the MXU eats (~0.2 GFLOP/level/image at 368×496),
    and out-of-range rows/cols select nothing — zero padding for free,
    matching grid_sample's padding_mode='zeros'.
    """
    B, H, W, _ = coords.shape
    N = H * W
    K = 2 * radius + 1
    P = K + 1
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)

    out = []
    for i, vol in enumerate(pyramid):
        Hl, Wl = vol.shape[-2:]
        x0, y0, wx, wy = _window_base(x / (2 ** i), y / (2 ** i), radius)
        taps = jnp.arange(P, dtype=jnp.int32)
        rows = y0[..., None] + taps                          # (B, N, P)
        cols = x0[..., None] + taps
        # Selection is EXACT at the volume's own dtype: each output element
        # is one volume entry times 1.0 (plus zeros), and 0/1 are exact in
        # bf16. So for the fp32 corr island (raft.py:102-103) force fp32
        # MXU passes (HIGHEST — default precision would round the entries
        # to bf16), while a bf16-stored volume (corr_dtype='bfloat16')
        # rides the MXU at native bf16 rate with bf16 one-hots — 4× the
        # fp32 rate and half the operand traffic, bit-identical to
        # selecting from the same bf16 volume in fp32.
        fp32_vol = vol.dtype == jnp.float32
        sel_dtype = jnp.float32 if fp32_vol else vol.dtype
        prec = HIGHEST if fp32_vol else None
        sel_y = (rows[..., None] == jnp.arange(Hl)).astype(sel_dtype)
        sel_x = (cols[..., None] == jnp.arange(Wl)).astype(sel_dtype)
        tmp = jnp.einsum("bnph,bnhw->bnpw", sel_y, vol,
                         precision=prec)                     # row select
        win = jnp.einsum("bnpw,bnqw->bnpq", tmp, sel_x,
                         precision=prec)                     # col select
        out.append(_separable_lerp(win.astype(jnp.float32), wx, wy, radius))
    return jnp.concatenate(out, axis=-1).reshape(B, H, W, -1)


def corr_lookup_softsel(pyramid: Sequence[jax.Array], coords: jax.Array,
                        radius: int) -> jax.Array:
    """One-hot lookup with the separable bilinear lerp FOLDED INTO the
    selection matrices.

    :func:`corr_lookup_onehot` selects an integer (2r+2)² window with 0/1
    one-hots and then lerps neighboring rows/columns — and that post-GEMM
    lerp chain runs on (B,N,P,P)/(B,N,P,Wl)-shaped tensors whose minor
    dims tile the (8,128) memory tile at 8-31% occupancy (measured ~60
    ms/step at chairs-b8, XProf session C). Here the selection matrices
    are "soft two-hots" carrying the bilinear weights directly::

        sel_y[b,n,k,h] = (1-wy)·[h == y0+k] + wy·[h == y0+k+1]

    so the two GEMMs produce the final K×K window and no lerp
    intermediates exist at all. Algebraically identical (separable
    bilinear interpolation distributes over the contractions);
    out-of-range taps still select nothing (zeros padding). With a bf16
    volume the weights ride in the bf16 GEMM — one extra rounding of the
    (exactly representable 0/1-range) fractional weights vs the onehot
    path's fp32 lerp; the fp32 island keeps HIGHEST + fp32 selections.
    """
    B, H, W, _ = coords.shape
    N = H * W
    K = 2 * radius + 1
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)

    out = []
    for i, vol in enumerate(pyramid):
        Hl, Wl = vol.shape[-2:]
        x0, y0, wx, wy = _window_base(x / (2 ** i), y / (2 ** i), radius)
        taps = jnp.arange(K, dtype=jnp.int32)
        rows = y0[..., None] + taps                      # (B, N, K)
        cols = x0[..., None] + taps
        fp32_vol = vol.dtype == jnp.float32
        sel_dtype = jnp.float32 if fp32_vol else vol.dtype
        prec = HIGHEST if fp32_vol else None
        ih = jnp.arange(Hl)
        iw = jnp.arange(Wl)
        wy_ = wy[..., None, None]
        wx_ = wx[..., None, None]
        sel_y = ((1.0 - wy_) * (rows[..., None] == ih)
                 + wy_ * (rows[..., None] + 1 == ih)).astype(sel_dtype)
        sel_x = ((1.0 - wx_) * (cols[..., None] == iw)
                 + wx_ * (cols[..., None] + 1 == iw)).astype(sel_dtype)
        tmp = jnp.einsum("bnkh,bnhw->bnkw", sel_y, vol,
                         precision=prec)                 # row select+lerp
        win = jnp.einsum("bnkw,bnqw->bnkq", tmp, sel_x,
                         precision=prec)                 # col select+lerp
        # (B, N, Ky, Kx) -> x-major flat channels
        out.append(jnp.swapaxes(win.astype(jnp.float32), -1, -2)
                   .reshape(B, N, K * K))
    return jnp.concatenate(out, axis=-1).reshape(B, H, W, -1)


def build_corr_pyramid_t(fmap1: jax.Array, fmap2: jax.Array,
                         num_levels: int = 4) -> List[jax.Array]:
    """Transposed volume pyramid: levels of (B, Hl, Wl, N) — TARGET pixels
    leading, the query index N = H·W on the minor (lane) axis.

    Same dot products as :func:`build_corr_pyramid` (identical einsum
    contraction over C, so bit-identical values — only the storage order
    differs). Why: the (B, N, Hl, Wl) layout puts (46, 62)-ish dims into
    the TPU's (8,128) memory tile at ~47% occupancy, and every lookup
    intermediate downstream of it inherits (P, Wl)/(P, P) minor dims at
    6-12% occupancy — measured at ~20% of the whole r3 train step (XProf,
    fusion.2000-2013 group at 28-35 GB/s). With N on lanes every lookup
    tensor tiles at ≥94% occupancy, and the pyramid pool is a plain NHWC
    window reduce with N as the channel axis.
    """
    B, H, W, C = fmap1.shape
    f1 = fmap1.astype(jnp.float32).reshape(B, H * W, C)
    f2 = fmap2.astype(jnp.float32).reshape(B, H * W, C)
    corr = jnp.einsum("byc,bxc->byx", f2, f1, precision=HIGHEST)
    corr = (corr / math.sqrt(C)).reshape(B, H, W, H * W)
    pyramid = [corr]
    for _ in range(num_levels - 1):
        corr = avg_pool2x2(corr)
        pyramid.append(corr)
    return pyramid


def corr_lookup_onehot_t(pyramid_t: Sequence[jax.Array], coords: jax.Array,
                         radius: int) -> jax.Array:
    """One-hot selection lookup over the TRANSPOSED pyramid (pixels on
    lanes). Same math as :func:`corr_lookup_onehot` — integer (2r+2)²
    window select via two one-hot contractions, then the separable 2-tap
    lerp — with every operand and intermediate keeping N minor.
    """
    B, H, W, _ = coords.shape
    N = H * W
    K = 2 * radius + 1
    P = K + 1
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)

    out = []
    for i, vol in enumerate(pyramid_t):
        Hl, Wl = vol.shape[1:3]
        x0, y0, wx, wy = _window_base(x / (2 ** i), y / (2 ** i), radius)
        taps = jnp.arange(P, dtype=jnp.int32)
        rows = jnp.swapaxes(y0[..., None] + taps, 1, 2)   # (B, P, N)
        cols = jnp.swapaxes(x0[..., None] + taps, 1, 2)
        fp32_vol = vol.dtype == jnp.float32
        sel_dtype = jnp.float32 if fp32_vol else vol.dtype
        prec = HIGHEST if fp32_vol else None
        # one-hots (B, P, Hl|Wl, N): out-of-range rows/cols select nothing
        # (zero padding for free), as in corr_lookup_onehot
        sel_y = (rows[:, :, None, :]
                 == jnp.arange(Hl)[:, None]).astype(sel_dtype)
        sel_x = (cols[:, :, None, :]
                 == jnp.arange(Wl)[:, None]).astype(sel_dtype)
        tmp = jnp.einsum("bphn,bhwn->bpwn", sel_y, vol,
                         precision=prec)                  # row select
        win = jnp.einsum("bqwn,bpwn->bpqn", sel_x, tmp,
                         precision=prec)                  # col select
        out.append(_separable_lerp_t(win.astype(jnp.float32), wx, wy,
                                     radius))
    return jnp.concatenate(out, axis=-1).reshape(B, H, W, -1)


def corr_lookup_softsel_t(pyramid_t: Sequence[jax.Array], coords: jax.Array,
                          radius: int) -> jax.Array:
    """:func:`corr_lookup_softsel`'s lerp-folded soft two-hot selection
    composed with :func:`corr_lookup_onehot_t`'s TRANSPOSED
    (pixels-on-lanes) volume layout.

    Motivation (XProf, round 5): at the r5 ladder winner the softsel
    selection GEMMs and their backwards were ~30% of the train step —
    their (B, N, K, Wl) intermediates tile the (8,128) memory tile at
    ~27% occupancy (20-80 GB/s effective). Here every selection operand,
    intermediate, and the volume itself keep the query index N minor
    (lane-clean), while the bilinear lerp still rides inside the
    selection GEMMs with no lerp intermediates. Same math as softsel;
    same zeros-for-out-of-range semantics.
    """
    B, H, W, _ = coords.shape
    N = H * W
    K = 2 * radius + 1
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)

    out = []
    for i, vol in enumerate(pyramid_t):
        Hl, Wl = vol.shape[1:3]
        x0, y0, wx, wy = _window_base(x / (2 ** i), y / (2 ** i), radius)
        taps = jnp.arange(K, dtype=jnp.int32)
        rows = jnp.swapaxes(y0[..., None] + taps, 1, 2)   # (B, K, N)
        cols = jnp.swapaxes(x0[..., None] + taps, 1, 2)
        fp32_vol = vol.dtype == jnp.float32
        sel_dtype = jnp.float32 if fp32_vol else vol.dtype
        prec = HIGHEST if fp32_vol else None
        ih = jnp.arange(Hl)[:, None]
        iw = jnp.arange(Wl)[:, None]
        wy_ = wy[:, None, None, :]                        # (B, 1, 1, N)
        wx_ = wx[:, None, None, :]
        r_ = rows[:, :, None, :]                          # (B, K, 1, N)
        c_ = cols[:, :, None, :]
        sel_y = ((1.0 - wy_) * (r_ == ih)
                 + wy_ * (r_ + 1 == ih)).astype(sel_dtype)  # (B, K, Hl, N)
        sel_x = ((1.0 - wx_) * (c_ == iw)
                 + wx_ * (c_ + 1 == iw)).astype(sel_dtype)
        tmp = jnp.einsum("bkhn,bhwn->bkwn", sel_y, vol,
                         precision=prec)                  # row select+lerp
        win = jnp.einsum("bqwn,bkwn->bkqn", sel_x, tmp,
                         precision=prec)                  # col select+lerp
        # (B, Ky, Kx, N) -> x-major flat channels
        out.append(jnp.transpose(win.astype(jnp.float32), (0, 3, 2, 1))
                   .reshape(B, N, K * K))
    return jnp.concatenate(out, axis=-1).reshape(B, H, W, -1)


def _separable_lerp_t(win: jax.Array, wx: jax.Array, wy: jax.Array,
                      radius: int) -> jax.Array:
    """(B, P, P, N) [y, x] window -> (B, N, K²) x-major channels."""
    K = 2 * radius + 1
    wy_ = wy[:, None, None, :]                            # (B, 1, 1, N)
    wx_ = wx[:, None, None, :]
    wl = (1.0 - wy_) * win[:, :K] + wy_ * win[:, 1:]
    o = (1.0 - wx_) * wl[:, :, :K] + wx_ * wl[:, :, 1:]   # (B, Ky, Kx, N)
    # x-major flat channels (module docstring layout contract)
    return jnp.transpose(o, (0, 3, 2, 1)).reshape(win.shape[0], -1, K * K)


class CorrBlock:
    """Materialized-pyramid path (corr.py:12-60)."""

    def __init__(self, fmap1: jax.Array, fmap2: jax.Array,
                 num_levels: int = 4, radius: int = 4):
        self.num_levels = num_levels
        self.radius = radius
        self.pyramid = build_corr_pyramid(fmap1, fmap2, num_levels)

    def __call__(self, coords: jax.Array) -> jax.Array:
        return corr_lookup(self.pyramid, coords, self.radius)


# ---------------------------------------------------------------------------
# Memory-efficient path (alt_cuda_corr equivalent)
# ---------------------------------------------------------------------------


def _gather_bilinear_fmap(fmap: jax.Array, xs: jax.Array, ys: jax.Array
                          ) -> jax.Array:
    """Bilinear-sample ``fmap`` (B, H, W, C) at (B, N, P) points -> (B,N,P,C)."""
    B, H, W, C = fmap.shape
    flat = fmap.reshape(B, H * W, C)

    x0 = jnp.floor(xs)
    y0 = jnp.floor(ys)
    wx = xs - x0
    wy = ys - y0

    def corner(xi, yi, w):
        valid = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xi_c = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        idx = (yi_c * W + xi_c).reshape(B, -1)           # (B, N*P)
        vals = jnp.take_along_axis(flat, idx[..., None], axis=1)
        vals = vals.reshape(*xi.shape, C)                 # (B, N, P, C)
        w = (w * valid.astype(jnp.float32))[..., None]
        return vals * w

    return (
        corner(x0, y0, (1 - wx) * (1 - wy))
        + corner(x0 + 1, y0, wx * (1 - wy))
        + corner(x0, y0 + 1, (1 - wx) * wy)
        + corner(x0 + 1, y0 + 1, wx * wy)
    )


def alt_corr_lookup(fmap1: jax.Array, fmap2_pyramid: Sequence[jax.Array],
                    coords: jax.Array, radius: int,
                    chunk: int = 4096) -> jax.Array:
    """On-the-fly windowed correlation, never materializing (HW)².

    For each level: sample fmap2 at the window points, dot with fmap1.
    Chunked over query pixels to bound the (chunk, K², C) intermediate —
    the VMEM-sized tiling a Pallas kernel would use, expressed at the XLA
    level. Matches ``AlternateCorrBlock`` (corr.py:63-91) which normalizes
    once by sqrt(dim of level-0 fmap).
    """
    B, H, W, C = fmap1.shape
    N = H * W
    du, dv = _window_offsets(radius)
    K2 = du.shape[0]

    f1 = fmap1.astype(jnp.float32).reshape(B, N, C)
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)

    n_chunks = max(1, -(-N // chunk))
    pad = n_chunks * chunk - N
    if pad:
        f1 = jnp.pad(f1, ((0, 0), (0, pad), (0, 0)))
        x = jnp.pad(x, ((0, 0), (0, pad)))
        y = jnp.pad(y, ((0, 0), (0, pad)))

    f1 = f1.reshape(B, n_chunks, chunk, C).transpose(1, 0, 2, 3)
    x = x.reshape(B, n_chunks, chunk).transpose(1, 0, 2)
    y = y.reshape(B, n_chunks, chunk).transpose(1, 0, 2)

    def process_chunk(args):
        f1_c, x_c, y_c = args  # (B, chunk, C), (B, chunk)
        outs = []
        for i, f2 in enumerate(fmap2_pyramid):
            xs = x_c[..., None] / (2 ** i) + du[None, None, :]
            ys = y_c[..., None] / (2 ** i) + dv[None, None, :]
            f2v = _gather_bilinear_fmap(f2.astype(jnp.float32), xs, ys)
            corr = jnp.einsum("bnkc,bnc->bnk", f2v, f1_c, precision=HIGHEST)
            outs.append(corr)
        return jnp.concatenate(outs, axis=-1)  # (B, chunk, L*K²)

    out = jax.lax.map(process_chunk, (f1, x, y))  # (n_chunks, B, chunk, LK²)
    out = out.transpose(1, 0, 2, 3).reshape(B, n_chunks * chunk, -1)
    if pad:
        out = out[:, :N]
    return (out / math.sqrt(C)).reshape(B, H, W, -1)


class AlternateCorrBlock:
    """Memory-efficient path (corr.py:63-91 + alt_cuda_corr).

    Builds the pooled fmap2 pyramid once; per call recomputes windowed
    correlation. Note the reference builds num_levels+1 pyramid entries but
    only indexes 0..num_levels-1 and always level-0 fmap1
    (corr.py:68-72,82-83) — we build only what is used.
    """

    def __init__(self, fmap1: jax.Array, fmap2: jax.Array,
                 num_levels: int = 4, radius: int = 4, chunk: int = 4096):
        self.radius = radius
        self.chunk = chunk
        self.fmap1 = fmap1
        self.fmap2_pyramid = [fmap2]
        f2 = fmap2
        for _ in range(num_levels - 1):
            f2 = avg_pool2x2(f2)
            self.fmap2_pyramid.append(f2)

    def __call__(self, coords: jax.Array) -> jax.Array:
        return alt_corr_lookup(self.fmap1, self.fmap2_pyramid, coords,
                               self.radius, self.chunk)
