from raft_tpu.models.corr import (  # noqa: F401
    AlternateCorrBlock,
    CorrBlock,
    alt_corr_lookup,
    build_corr_pyramid,
    corr_lookup,
)
from raft_tpu.models.encoders import BasicEncoder, SmallEncoder  # noqa: F401
from raft_tpu.models.layers import (  # noqa: F401
    BottleneckBlock,
    Norm,
    ResidualBlock,
    TorchConv,
    instance_norm,
)
from raft_tpu.models.raft import RAFT, create_raft  # noqa: F401
from raft_tpu.models.update import (  # noqa: F401
    BasicUpdateBlock,
    ConvGRU,
    FlowHead,
    SepConvGRU,
    SmallUpdateBlock,
)
