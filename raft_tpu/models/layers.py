"""Building-block layers: conv init, norms, residual blocks (NHWC, flax).

Equivalents of ``/root/reference/core/extractor.py:6-116`` with the four norm
variants. Parameter layouts are flax-native (HWIO kernels, channels-last);
the checkpoint converter handles the OIHW transpose.

Padding note: torch ``Conv2d(padding=p)`` pads symmetrically by p. XLA
``'SAME'`` pads asymmetrically for strided convs (low side gets less), which
shifts windows by one pixel on even sizes — so every conv here uses explicit
torch-style symmetric padding.

Norm parity notes (torch defaults the reference relies on):
- ``nn.InstanceNorm2d(planes)`` has ``affine=False, track_running_stats=False``
  -> parameter-free, always per-sample stats. Stateless function here.
- ``nn.BatchNorm2d``: torch momentum 0.1 == flax momentum 0.9; eps 1e-5.
- ``nn.GroupNorm``: affine, eps 1e-5, ``num_groups = planes // 8``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

Dtype = Any

# Kaiming-normal fan_out/relu, matching extractor.py:150-157.
kaiming_normal = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def torch_bias_init(fan_in: int) -> Callable:
    """torch Conv2d default bias init: U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""

    def init(key, shape, dtype=jnp.float32):
        bound = 1.0 / np.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


class TorchConv(nn.Module):
    """NHWC conv matching torch ``Conv2d(k, stride, padding)`` semantics.

    ``padding`` is torch-style: symmetric (ph, pw) pixels. Params stored
    fp32; compute in ``dtype`` (the mixed-precision autocast analog).
    """

    features: int
    kernel_size: tuple
    strides: tuple = (1, 1)
    padding: tuple = (0, 0)
    dtype: Dtype = jnp.float32
    use_bias: bool = True

    def __call__(self, x):
        kernel, bias = self.weights(x.shape[-1])
        return _apply_conv(x, kernel, bias, self.strides, self.padding,
                           self.dtype)

    @nn.compact
    def weights(self, in_feat):
        """Declare/return (kernel, bias) without convolving — the single
        param-declaring method (identical tree and init whether the conv
        is applied via ``__call__`` or fused by a parent into a wider
        conv over a shared input, see :func:`fused_conv_pair`)."""
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel", kaiming_normal, (kh, kw, in_feat, self.features),
            jnp.float32,
        )
        bias = self.param(
            "bias", torch_bias_init(in_feat * kh * kw), (self.features,),
            jnp.float32,
        ) if self.use_bias else None
        return kernel, bias


def _apply_conv(x, kernel, bias, strides, padding, dtype):
    """The one conv-application recipe (cast, torch-style symmetric pad,
    NHWC/HWIO dimension numbers, bias cast/add) shared by
    ``TorchConv.__call__`` and :func:`fused_conv_pair`."""
    ph, pw = padding
    y = jax.lax.conv_general_dilated(
        x.astype(dtype),
        kernel.astype(dtype),
        window_strides=strides,
        padding=((ph, ph), (pw, pw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if bias is not None:
        y = y + bias.astype(dtype)
    return y


def _concat_pair_weights(conv_a: "TorchConv", conv_b: "TorchConv", in_feat):
    """Declare two same-geometry TorchConvs' params and return them
    concatenated on the output-channel axis — the ONE definition of the
    pair-fusion contract, shared by the NHWC and lane-major pair paths
    (a change to fusability must not silently diverge them)."""
    assert (conv_a.kernel_size == conv_b.kernel_size
            and conv_a.strides == conv_b.strides
            and conv_a.padding == conv_b.padding
            and conv_a.dtype == conv_b.dtype
            and conv_a.use_bias == conv_b.use_bias), "fusable convs must agree"
    ka, ba = conv_a.weights(in_feat)
    kb, bb = conv_b.weights(in_feat)
    kernel = jnp.concatenate([ka, kb], axis=-1)
    bias = jnp.concatenate([ba, bb]) if ba is not None else None
    return kernel, bias


def fused_conv_pair(conv_a: "TorchConv", conv_b: "TorchConv", x):
    """Apply two same-geometry TorchConvs to the SAME input as one
    double-width conv (kernels/biases concatenated on the output-channel
    axis), returning the pair of outputs.

    Each output channel's dot product is computed exactly as in the
    separate convs — the fusion only changes how many channels one
    conv_general_dilated emits — so values are identical; what it buys
    is one larger TPU op instead of two small ones. The refinement-scan
    GRUs run at 46x62-ish spatial where per-op overhead dominates
    (measured: the scan-body conv fusions sit at 20-80 GB/s effective,
    XProf round 5), so halving the op count on the z/r gate pair is the
    lever. Param trees stay those of the two separate convs — checkpoint
    conversion (tools/convert) is unaffected.
    """
    kernel, bias = _concat_pair_weights(conv_a, conv_b, x.shape[-1])
    y = _apply_conv(x, kernel, bias, conv_a.strides, conv_a.padding,
                    conv_a.dtype)
    return y[..., :conv_a.features], y[..., conv_a.features:]


# Below this input width the per-tap contraction is expressed as
# broadcast FMAs instead of a dot: a cin of 2 (the 7x7-on-flow conv) pads
# its contraction dim to the MXU tile and pays layout assignment around
# the dot for no arithmetic win — PROFILE lesson 5 (a tiny contraction
# axis is not a GEMM; let the VPU stream).
_FMA_MAX_CIN = 8


def _apply_conv_lane_major(x, kernel, bias, hw, padding, dtype):
    """Stride-1 torch-padded conv in the lane-major ``(B, H·W, C)`` layout.

    The conv is a per-tap shifted GEMM accumulation: for each of the
    kh·kw kernel taps, the symmetrically padded input plane is shifted by
    the tap offset (a static slice), flattened back to ``(B, H·W, cin)``,
    and contracted against that tap's ``(cin, cout)`` kernel slice. Each
    output channel's dot product sums the same terms as
    ``conv_general_dilated`` — values match the NHWC conv to fp32
    accumulation-order noise — but every operand the MXU sees is
    ``(H·W, C)``-minor: the whole spatial plane on sublanes, channels on
    lanes, no per-op halo fragmentation. This is the scan-body layout
    lever for the 46x62-spatial GRU/motion-encoder convs that run
    latency-bound as small NHWC convs (PROFILE round 5 tail).

    ``x``: (B, H·W, cin); ``hw``: the (H, W) the flat axis factors into;
    ``kernel``: (kh, kw, cin, cout) HWIO as :class:`TorchConv` declares;
    ``padding``: torch-style symmetric (ph, pw). Returns (B, H·W, cout).
    """
    H, W = hw
    kh, kw, cin, cout = kernel.shape
    ph, pw = padding
    B, N, _ = x.shape
    assert N == H * W, (N, hw)
    assert x.shape[-1] == cin, (x.shape, kernel.shape)
    # the NHWC-conv equivalence below holds only for 'same'-shaped
    # geometry (stride 1, odd kernel, p = k//2): anything else changes
    # the output extent and this formulation would silently crop it
    assert kh == 2 * ph + 1 and kw == 2 * pw + 1, (
        "lane-major path covers torch-'same' convs only", kernel.shape,
        padding)
    x = x.astype(dtype)
    kernel = kernel.astype(dtype)
    if (kh, kw) == (1, 1):
        # pointwise conv: already one tile-dense GEMM, no shifts needed
        y = jnp.dot(x, kernel[0, 0])
    else:
        # reshape to the plane (free on a contiguous row-major layout),
        # pad once, slice per tap
        xp = jnp.pad(x.reshape(B, H, W, cin),
                     ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        y = None
        for dy in range(kh):
            for dx in range(kw):
                tap = jax.lax.slice(
                    xp, (0, dy, dx, 0),
                    (B, dy + H, dx + W, cin)).reshape(B, N, cin)
                if cin <= _FMA_MAX_CIN:
                    t = tap[..., 0:1] * kernel[dy, dx, 0]
                    for c in range(1, cin):
                        t = t + tap[..., c:c + 1] * kernel[dy, dx, c]
                else:
                    t = jnp.dot(tap, kernel[dy, dx])
                y = t if y is None else y + t
    if bias is not None:
        y = y + bias.astype(dtype)
    return y


def conv_lane_major(conv: "TorchConv", x, hw):
    """Apply a :class:`TorchConv` submodule to lane-major input.

    Declares the conv's parameters through ``TorchConv.weights`` — the
    tree is identical whether the module is applied NHWC via
    ``__call__`` or lane-major here, so the fused update block shares
    checkpoints with the reference-shaped one (the ``fused_conv_pair``
    contract, extended to a layout change).
    """
    kernel, bias = conv.weights(x.shape[-1])
    assert conv.strides == (1, 1), "lane-major path is stride-1 only"
    return _apply_conv_lane_major(x, kernel, bias, hw, conv.padding,
                                  conv.dtype)


def conv_pair_lane_major(conv_a: "TorchConv", conv_b: "TorchConv", x, hw):
    """Lane-major analog of :func:`fused_conv_pair`: two same-geometry
    convs over the SAME input as one double-width tap contraction
    (kernels/biases concatenated on the output-channel axis), returning
    the pair of outputs. Halves the per-tap GEMM count for the GRU's z/r
    gate pair, exactly as the NHWC fusion does for the conv count."""
    assert conv_a.strides == (1, 1), "lane-major path is stride-1 only"
    kernel, bias = _concat_pair_weights(conv_a, conv_b, x.shape[-1])
    y = _apply_conv_lane_major(x, kernel, bias, hw, conv_a.padding,
                               conv_a.dtype)
    return y[..., :conv_a.features], y[..., conv_a.features:]


def conv3x3(features, stride=1, dtype=jnp.float32, name=None):
    return TorchConv(features, (3, 3), (stride, stride), (1, 1), dtype,
                     name=name)


def conv1x1(features, stride=1, dtype=jnp.float32, name=None):
    return TorchConv(features, (1, 1), (stride, stride), (0, 0), dtype,
                     name=name)


def instance_norm(x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Parameter-free instance norm over H, W (torch InstanceNorm2d defaults)."""
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=(1, 2), keepdims=True)
    var = x32.var(axis=(1, 2), keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return out.astype(x.dtype)


class Norm(nn.Module):
    """Dispatch over the reference's 4 norm options (extractor.py:16-38).

    ``use_running_average`` only affects 'batch'; passing True implements both
    eval mode and ``freeze_bn`` (core/raft.py:58-61). Norms compute in fp32
    (torch autocast always runs norms fp32).
    """

    norm_fn: str  # 'group' | 'batch' | 'instance' | 'none'
    features: int
    num_groups: Optional[int] = None  # default features // 8 as reference

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        if self.norm_fn == "group":
            groups = self.num_groups if self.num_groups else self.features // 8
            return nn.GroupNorm(num_groups=groups, epsilon=1e-5,
                                dtype=jnp.float32, name="norm")(x)
        if self.norm_fn == "batch":
            return nn.BatchNorm(
                use_running_average=use_running_average,
                momentum=0.9, epsilon=1e-5, dtype=jnp.float32, name="norm",
            )(x)
        if self.norm_fn == "instance":
            return instance_norm(x)
        return x  # 'none'


class ResidualBlock(nn.Module):
    """Two 3x3 convs + skip (extractor.py:6-56)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        y = conv3x3(self.planes, self.stride, self.dtype, name="conv1")(x)
        y = Norm(self.norm_fn, self.planes, name="norm1")(y, use_running_average)
        y = nn.relu(y)
        y = conv3x3(self.planes, 1, self.dtype, name="conv2")(y)
        y = Norm(self.norm_fn, self.planes, name="norm2")(y, use_running_average)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv1x1(self.planes, self.stride, self.dtype,
                        name="downsample_conv")(x)
            x = Norm(self.norm_fn, self.planes, name="norm3")(
                x, use_running_average)

        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck + skip (extractor.py:60-116)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        p4 = self.planes // 4
        # reference num_groups = planes//8 for ALL norms in the block,
        # including the planes//4-channel ones (extractor.py:69-74).
        g = self.planes // 8
        y = conv1x1(p4, 1, self.dtype, name="conv1")(x)
        y = Norm(self.norm_fn, p4, num_groups=g, name="norm1")(
            y, use_running_average)
        y = nn.relu(y)
        y = conv3x3(p4, self.stride, self.dtype, name="conv2")(y)
        y = Norm(self.norm_fn, p4, num_groups=g, name="norm2")(
            y, use_running_average)
        y = nn.relu(y)
        y = conv1x1(self.planes, 1, self.dtype, name="conv3")(y)
        y = Norm(self.norm_fn, self.planes, num_groups=g, name="norm3")(
            y, use_running_average)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv1x1(self.planes, self.stride, self.dtype,
                        name="downsample_conv")(x)
            x = Norm(self.norm_fn, self.planes, num_groups=g, name="norm4")(
                x, use_running_average)

        return nn.relu(x + y)
