"""Building-block layers: conv init, norms, residual blocks (NHWC, flax).

Equivalents of ``/root/reference/core/extractor.py:6-116`` with the four norm
variants. Parameter layouts are flax-native (HWIO kernels, channels-last);
the checkpoint converter handles the OIHW transpose.

Padding note: torch ``Conv2d(padding=p)`` pads symmetrically by p. XLA
``'SAME'`` pads asymmetrically for strided convs (low side gets less), which
shifts windows by one pixel on even sizes — so every conv here uses explicit
torch-style symmetric padding.

Norm parity notes (torch defaults the reference relies on):
- ``nn.InstanceNorm2d(planes)`` has ``affine=False, track_running_stats=False``
  -> parameter-free, always per-sample stats. Stateless function here.
- ``nn.BatchNorm2d``: torch momentum 0.1 == flax momentum 0.9; eps 1e-5.
- ``nn.GroupNorm``: affine, eps 1e-5, ``num_groups = planes // 8``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

Dtype = Any

# Kaiming-normal fan_out/relu, matching extractor.py:150-157.
kaiming_normal = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def torch_bias_init(fan_in: int) -> Callable:
    """torch Conv2d default bias init: U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""

    def init(key, shape, dtype=jnp.float32):
        bound = 1.0 / np.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


class TorchConv(nn.Module):
    """NHWC conv matching torch ``Conv2d(k, stride, padding)`` semantics.

    ``padding`` is torch-style: symmetric (ph, pw) pixels. Params stored
    fp32; compute in ``dtype`` (the mixed-precision autocast analog).
    """

    features: int
    kernel_size: tuple
    strides: tuple = (1, 1)
    padding: tuple = (0, 0)
    dtype: Dtype = jnp.float32
    use_bias: bool = True

    def __call__(self, x):
        kernel, bias = self.weights(x.shape[-1])
        return _apply_conv(x, kernel, bias, self.strides, self.padding,
                           self.dtype)

    @nn.compact
    def weights(self, in_feat):
        """Declare/return (kernel, bias) without convolving — the single
        param-declaring method (identical tree and init whether the conv
        is applied via ``__call__`` or fused by a parent into a wider
        conv over a shared input, see :func:`fused_conv_pair`)."""
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel", kaiming_normal, (kh, kw, in_feat, self.features),
            jnp.float32,
        )
        bias = self.param(
            "bias", torch_bias_init(in_feat * kh * kw), (self.features,),
            jnp.float32,
        ) if self.use_bias else None
        return kernel, bias


def _apply_conv(x, kernel, bias, strides, padding, dtype):
    """The one conv-application recipe (cast, torch-style symmetric pad,
    NHWC/HWIO dimension numbers, bias cast/add) shared by
    ``TorchConv.__call__`` and :func:`fused_conv_pair`."""
    ph, pw = padding
    y = jax.lax.conv_general_dilated(
        x.astype(dtype),
        kernel.astype(dtype),
        window_strides=strides,
        padding=((ph, ph), (pw, pw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if bias is not None:
        y = y + bias.astype(dtype)
    return y


def fused_conv_pair(conv_a: "TorchConv", conv_b: "TorchConv", x):
    """Apply two same-geometry TorchConvs to the SAME input as one
    double-width conv (kernels/biases concatenated on the output-channel
    axis), returning the pair of outputs.

    Each output channel's dot product is computed exactly as in the
    separate convs — the fusion only changes how many channels one
    conv_general_dilated emits — so values are identical; what it buys
    is one larger TPU op instead of two small ones. The refinement-scan
    GRUs run at 46x62-ish spatial where per-op overhead dominates
    (measured: the scan-body conv fusions sit at 20-80 GB/s effective,
    XProf round 5), so halving the op count on the z/r gate pair is the
    lever. Param trees stay those of the two separate convs — checkpoint
    conversion (tools/convert) is unaffected.
    """
    assert (conv_a.kernel_size == conv_b.kernel_size
            and conv_a.strides == conv_b.strides
            and conv_a.padding == conv_b.padding
            and conv_a.dtype == conv_b.dtype
            and conv_a.use_bias == conv_b.use_bias), "fusable convs must agree"
    in_feat = x.shape[-1]
    ka, ba = conv_a.weights(in_feat)
    kb, bb = conv_b.weights(in_feat)
    kernel = jnp.concatenate([ka, kb], axis=-1)
    bias = jnp.concatenate([ba, bb]) if ba is not None else None
    y = _apply_conv(x, kernel, bias, conv_a.strides, conv_a.padding,
                    conv_a.dtype)
    return y[..., :conv_a.features], y[..., conv_a.features:]


def conv3x3(features, stride=1, dtype=jnp.float32, name=None):
    return TorchConv(features, (3, 3), (stride, stride), (1, 1), dtype,
                     name=name)


def conv1x1(features, stride=1, dtype=jnp.float32, name=None):
    return TorchConv(features, (1, 1), (stride, stride), (0, 0), dtype,
                     name=name)


def instance_norm(x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Parameter-free instance norm over H, W (torch InstanceNorm2d defaults)."""
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=(1, 2), keepdims=True)
    var = x32.var(axis=(1, 2), keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return out.astype(x.dtype)


class Norm(nn.Module):
    """Dispatch over the reference's 4 norm options (extractor.py:16-38).

    ``use_running_average`` only affects 'batch'; passing True implements both
    eval mode and ``freeze_bn`` (core/raft.py:58-61). Norms compute in fp32
    (torch autocast always runs norms fp32).
    """

    norm_fn: str  # 'group' | 'batch' | 'instance' | 'none'
    features: int
    num_groups: Optional[int] = None  # default features // 8 as reference

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        if self.norm_fn == "group":
            groups = self.num_groups if self.num_groups else self.features // 8
            return nn.GroupNorm(num_groups=groups, epsilon=1e-5,
                                dtype=jnp.float32, name="norm")(x)
        if self.norm_fn == "batch":
            return nn.BatchNorm(
                use_running_average=use_running_average,
                momentum=0.9, epsilon=1e-5, dtype=jnp.float32, name="norm",
            )(x)
        if self.norm_fn == "instance":
            return instance_norm(x)
        return x  # 'none'


class ResidualBlock(nn.Module):
    """Two 3x3 convs + skip (extractor.py:6-56)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        y = conv3x3(self.planes, self.stride, self.dtype, name="conv1")(x)
        y = Norm(self.norm_fn, self.planes, name="norm1")(y, use_running_average)
        y = nn.relu(y)
        y = conv3x3(self.planes, 1, self.dtype, name="conv2")(y)
        y = Norm(self.norm_fn, self.planes, name="norm2")(y, use_running_average)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv1x1(self.planes, self.stride, self.dtype,
                        name="downsample_conv")(x)
            x = Norm(self.norm_fn, self.planes, name="norm3")(
                x, use_running_average)

        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck + skip (extractor.py:60-116)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        p4 = self.planes // 4
        # reference num_groups = planes//8 for ALL norms in the block,
        # including the planes//4-channel ones (extractor.py:69-74).
        g = self.planes // 8
        y = conv1x1(p4, 1, self.dtype, name="conv1")(x)
        y = Norm(self.norm_fn, p4, num_groups=g, name="norm1")(
            y, use_running_average)
        y = nn.relu(y)
        y = conv3x3(p4, self.stride, self.dtype, name="conv2")(y)
        y = Norm(self.norm_fn, p4, num_groups=g, name="norm2")(
            y, use_running_average)
        y = nn.relu(y)
        y = conv1x1(self.planes, 1, self.dtype, name="conv3")(y)
        y = Norm(self.norm_fn, self.planes, num_groups=g, name="norm3")(
            y, use_running_average)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv1x1(self.planes, self.stride, self.dtype,
                        name="downsample_conv")(x)
            x = Norm(self.norm_fn, self.planes, num_groups=g, name="norm4")(
                x, use_running_average)

        return nn.relu(x + y)
