from raft_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
)
