from raft_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
)
from raft_tpu.parallel.partitioner import (  # noqa: F401
    PARTITION_RULES,
    Partitioner,
    mesh_model_config,
)
