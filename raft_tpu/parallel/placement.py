"""Placement: device assignment + dispatch-mode decisions for the
replica fleet.

The scheduler used to own these decisions implicitly — ``_route`` /
``ensure_bucket`` calls straight into ONE engine meant "this bucket
runs on that engine's device, data-parallel never, always". With the
fleet (``MicroBatchScheduler(replicas=N)``) those are real decisions,
and this module is their single owner, sitting over the
:class:`~raft_tpu.parallel.partitioner.Partitioner` seam:

- **Replica construction + device assignment**: replicas 2..N are
  siblings of the primary engine (``RAFTEngine.spawn_replica``) sharing
  its AOT artifact store, so each added replica warms by LOADING the
  serialized executables the primary already produced — zero extra XLA
  compiles per replica, counter-pinned. Each replica gets a NOMINAL
  device from a round-robin over the local device table; on the forced
  CPU mesh the assignment is observability (it names which device a
  real multi-chip deployment would pin), on real hardware it is the
  pinning input.
- **Per-bucket dispatch mode** (:meth:`decide`): data-parallel
  ``"replicate"`` by default — N replicas each run whole micro-batches
  — versus ``"shard"`` for 4K-class frames whose single-pair FLOPs are
  worth splitting across the mesh: those buckets pin to the PRIMARY
  lane (the engine that carries the ``Partitioner``/mesh, compiling a
  pjit-sharded batch), because a spatially-sharded program and a
  replica-local program are different executables with different
  failure domains.
- **Bucket capacity/warming** (:meth:`bucket_fit`): the
  capacity-probe-or-ensure logic refactored OUT of the scheduler's
  ``_shape_capacity`` — one copy, engine-parametric, so every replica
  warms its bucket exactly the way the single engine always did
  (byte-identical at ``replicas=1``).
- **Scaling policy** (:meth:`want_scale_up` / :meth:`want_retire`):
  queue-depth-driven activation up to a configured ceiling, idle-time
  retirement back down to the configured floor.

Deliberately jax-light: nothing here compiles or device_puts; the
engines do. A duck-typed engine without ``spawn_replica`` works at
``replicas=1`` (no spawning happens) or with an explicit ``engines``
list (the tests' fake-fleet path).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

#: graftthread declarations, now that raft_tpu/parallel/ sits inside
#: the argument-less gate scope: Placement is deliberately LOCK-FREE —
#: the scheduler calls every method here while holding its own locks
#: (lane selection under ``_cv``, host marks from the verdict path),
#: so this layer must never acquire anything of its own (a lock here
#: would nest under every scheduler lock and belong in its
#: LOCK_ORDER). The empty chain is the declared contract, not an
#: omission; graftthread verifies no ``with <lock>`` ever appears.
LOCK_ORDER = ()

GRAFTTHREAD = {
    "locks": (),
}

#: padded H*W at/above which a bucket is 4K-class: one pair's FLOPs are
#: worth pjit-sharding across the mesh instead of replicating the whole
#: micro-batch (2160x3840 = UHD)
SHARD_PX_THRESHOLD = 2160 * 3840


class Placement:
    """Device assignment + per-bucket dispatch mode for one variant's
    engine fleet.

    ``engine``: the primary (replica 0 — the one the registry built,
    possibly mesh-armed). ``replicas``: fleet floor — how many engines
    exist and start active. ``ceiling``: how many the scheduler may
    grow to under queue pressure (default: the floor — no growth).
    ``engines``: pre-built engine list overriding spawning (primary
    first; for tests/fakes). ``shard_px_threshold``: the 4K-class
    boundary for :meth:`decide`.
    """

    def __init__(self, engine, *, replicas: int = 1,
                 ceiling: Optional[int] = None,
                 engines: Optional[List] = None,
                 shard_px_threshold: int = SHARD_PX_THRESHOLD):
        self.primary = engine
        self.replicas = max(1, int(replicas))
        self.ceiling = (self.replicas if ceiling is None
                        else max(self.replicas, int(ceiling)))
        self.shard_px_threshold = int(shard_px_threshold)
        self.partitioner = getattr(engine, "partitioner", None)
        if engines is not None:
            if not engines or engines[0] is not engine:
                raise ValueError(
                    "engines must be the fleet's engine list with the "
                    "primary first")
            if len(engines) < self.replicas:
                raise ValueError(
                    f"engines has {len(engines)} entries but "
                    f"replicas={self.replicas}")
            self.engines = list(engines)
        else:
            self.engines = [engine]
            for _ in range(1, self.replicas):
                self.engines.append(self._spawn())
        #: replica index -> nominal device label (round-robin)
        self.assignments: Dict[int, str] = {
            k: self._device_label(k) for k in range(len(self.engines))}
        #: host name -> {"lane": index, "state": ...} for lanes that
        #: live on REMOTE hosts (serving/hosts.py); empty at hosts=0
        self.hosts: Dict[str, Dict] = {}

    # -- multi-host lanes ---------------------------------------------------

    def attach_host(self, name: str, engine) -> int:
        """Append a remote host's engine as one more fleet lane (after
        the local lanes, so local indices never move). The ceiling
        grows with it — host lanes are extra capacity, not consumers
        of the local-replica growth headroom. Returns the lane
        index."""
        k = len(self.engines)
        self.engines.append(engine)
        self.ceiling += 1
        self.assignments[k] = f"host:{name}"
        self.hosts[name] = {"lane": k, "state": "healthy"}
        return k

    def mark_host(self, name: str, state: str) -> None:
        """Record a host's liveness verdict (``healthy``/``suspect``/
        ``dead``) against its lane — the quarantine-on-the-placement-
        layer half of a dead-host verdict."""
        if name in self.hosts:
            self.hosts[name]["state"] = state

    def host_lane(self, name: str) -> Optional[int]:
        h = self.hosts.get(name)
        return None if h is None else h["lane"]

    # -- replica construction ---------------------------------------------

    def _spawn(self):
        spawn = getattr(self.primary, "spawn_replica", None)
        if spawn is None:
            raise ValueError(
                "replicas>1 needs an engine with spawn_replica (or an "
                "explicit engines list)")
        return spawn()

    def grow(self):
        """Build one more replica engine (scheduler scale-up past the
        constructed fleet, bounded by ``ceiling``); returns the new
        engine and records its nominal device."""
        if len(self.engines) >= self.ceiling:
            raise ValueError(
                f"fleet at ceiling ({self.ceiling}) — cannot grow")
        eng = self._spawn()
        k = len(self.engines)
        self.engines.append(eng)
        self.assignments[k] = self._device_label(k)
        return eng

    def _device_label(self, k: int) -> str:
        """Nominal device for replica ``k``: round-robin over the local
        device table. On the forced-host-platform CPU gate every label
        is a distinct cpu:i — the assignment a real deployment pins
        replicas by."""
        devs = self._devices()
        if not devs:
            return f"device:{k}"
        return str(devs[k % len(devs)])

    def _devices(self) -> List:
        try:
            import jax

            return list(jax.local_devices())
        except Exception:  # noqa: BLE001 — duck engines, no-jax tests
            return []

    # -- per-bucket dispatch mode -----------------------------------------

    def decide(self, key: Tuple) -> str:
        """Dispatch mode for a coalescing-group key (``(H, W)`` or the
        longer cached/ragged forms — dims 0/1 are always the spatial
        extents): ``"replicate"`` (default — whole micro-batches fan
        out across replica lanes) or ``"shard"`` (4K-class frames on a
        mesh-armed primary: the batch pjit-shards, so the bucket pins
        to the primary lane)."""
        if self.partitioner is None:
            return "replicate"
        h, w = int(key[0]), int(key[1])
        return ("shard" if h * w >= self.shard_px_threshold
                else "replicate")

    # -- bucket capacity / warming (ex scheduler._shape_capacity) ---------

    @staticmethod
    def bucket_fit(engine, key: Tuple, max_batch: int) -> int:
        """Capacity-probe-or-warm for one coalescing key on ONE engine
        — the logic the scheduler's ``_shape_capacity`` carried, now
        engine-parametric so each replica warms its own table (an AOT
        store turns the warm into a load, not a compile). Returns the
        bucket/class batch fit; may compile (never call under a
        lock)."""
        h, w = key[0], key[1]
        if len(key) > 2 and key[2] == "ragged":
            # capacity-class group: key dims ARE the class box.
            # Pre-warm ONE class at max_batch so every later fill
            # count (and shape mix) batch-fills into it — the H3
            # one-executable discipline, now across shapes.
            fit = engine.ragged_capacity(h, w)
            if fit is None:
                fit = engine.ensure_ragged(max_batch, h, w)[0]
        elif len(key) > 2:
            # feature-cache group: its own signature table — the
            # plain kwarg-less calls below stay byte-identical for
            # duck-typed engines without the cached API
            fit = engine.bucket_capacity(h, w, cached=True)
            if fit is None:
                fit = engine.ensure_bucket(max_batch, h, w,
                                           cached=True)[0]
        else:
            fit = engine.bucket_capacity(h, w)
            if fit is None:
                # no compiled bucket fits this spatial shape: pre-warm
                # exactly one at max_batch so every later fill count
                # batch-fills into it (executable count stays one per
                # shape, the H3 discipline). After a wedge dropped the
                # bucket, this is also the half-open probe's lazy
                # recompile.
                fit = engine.ensure_bucket(max_batch, h, w)[0]
        return fit

    # -- scaling policy ----------------------------------------------------

    def want_scale_up(self, queue_depth: int, active: int,
                      max_batch: int) -> bool:
        """Activate another replica when the queue holds more work
        than the active lanes can coalesce in one dispatch round each
        — sustained pressure, not a blip — and the ceiling allows."""
        return (active < self.ceiling
                and queue_depth > active * max(1, max_batch))

    def want_retire(self, idle_s: float, active: int,
                    idle_retire_s: float) -> bool:
        """Retire an idle lane back toward the configured floor."""
        return active > self.replicas and idle_s >= idle_retire_s

    # -- observability -----------------------------------------------------

    def snapshot(self) -> Dict:
        return {
            "replicas": len(self.engines),
            "floor": self.replicas,
            "ceiling": self.ceiling,
            "shard_px_threshold": self.shard_px_threshold,
            "mesh": self.partitioner is not None,
            "assignments": {f"r{k}": v
                            for k, v in sorted(self.assignments.items())},
            **({"hosts": {name: dict(h)
                          for name, h in sorted(self.hosts.items())}}
               if self.hosts else {}),
        }
