"""Partitioner: the one pjit seam the sharded programs share.

The serving fan-out (ROADMAP: replicas + sharding behind the registry)
needs RAFTEngine buckets that can compile as SPMD programs; the train
step already does (trainer.py's mesh). Before this module each site
carried its own copies of the same decisions — which values shard over
which mesh axes, what grain a geometry must divide, when a config needs
the mesh-safe encoder path. ``Partitioner`` is the single owner of
those DECISIONS over ``mesh.PARTITION_RULES`` — the one spec table,
which the legacy ``mesh.py`` helpers read too (the partition-rule-
matching idiom of the related pjit codebases, cut down to the five
logical value kinds this model serves) — consumed by

- ``RAFTEngine(mesh=...)`` — bucket sharding/validation/rounding;
- ``training.trainer`` — mesh-safe model config + replicated rng;
- ``tools/graftshard`` — the declared specs S4/S5 audit against.

Keeping the declarations HERE is what makes the graftshard audit
meaningful: the tier checks the same table the runtime shards with, so
a spec drift fails the gate instead of silently replicating a value.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# re-exported: the table itself lives in mesh.py (ONE copy, read by
# the legacy helpers, this seam, and the graftshard audit alike)
from raft_tpu.parallel.mesh import (PARTITION_RULES,  # noqa: F401
                                    validate_spatial_extent)


class Partitioner:
    """Sharding decisions for one ``(data, spatial)`` mesh."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.data = mesh.shape.get("data", 1)
        self.spatial = mesh.shape.get("spatial", 1)
        #: kind -> NamedSharding, built once: sharding() sits on the
        #: engine's per-dispatch path, which must not construct fresh
        #: spec objects per request (the mesh and the rule table are
        #: both fixed for this Partitioner's lifetime)
        self._shardings: dict = {}

    # -- specs ------------------------------------------------------------

    def spec(self, kind: str) -> P:
        return P(*PARTITION_RULES[kind])

    def sharding(self, kind: str) -> NamedSharding:
        got = self._shardings.get(kind)
        if got is None:
            got = NamedSharding(self.mesh, self.spec(kind))
            self._shardings[kind] = got
        return got

    @property
    def replicated(self) -> NamedSharding:
        return self.sharding("weights")

    # -- geometry ---------------------------------------------------------

    def grain(self) -> Tuple[int, int]:
        """(batch grain, height grain) a bucket must divide: whole
        examples per 'data' shard, whole ÷8 feature rows per 'spatial'
        shard. Single source for the compile-time check and the
        compile-on-miss rounding — the two must agree or the router's
        own ad-hoc buckets would fail the engine's validation."""
        return self.data, 8 * self.spatial

    def validate_extent(self, image_h: int) -> None:
        """Reject spatial shardings XLA cannot execute correctly (the
        in-scan conv-halo fence, ``mesh.validate_spatial_extent``)."""
        validate_spatial_extent(image_h, self.mesh)

    def validate_bucket(self, shape: Tuple[int, int, int]) -> None:
        """Raise unless a ``(B, H, W)`` bucket divides the mesh grain —
        an uneven bucket compiles fine and only fails later at
        device_put with an opaque uneven-sharding ValueError."""
        b, h, _ = shape
        bg, hg = self.grain()
        if b % bg or h % hg:
            raise ValueError(
                f"bucket {shape} is not mesh-divisible: batch must "
                f"be a multiple of data={bg} and height a "
                f"multiple of 8*spatial={hg}")

    def round_bucket(self, b: int, hp: int) -> Tuple[int, int]:
        """Round a compile-on-miss ``(batch, padded height)`` up to the
        mesh grain (zero-fill + output crop absorb the padding)."""
        bg, hg = self.grain()
        return -(-b // bg) * bg, -(-hp // hg) * hg

    # -- audit surface (tools/graftshard) ---------------------------------

    def declared_specs(self) -> Tuple[Tuple[str, Tuple[Optional[str], ...]],
                                      ...]:
        """``(value kind, axis names per dim)`` pairs — the S4 surface:
        every named axis must exist on the mesh the program compiles
        against."""
        return tuple((k, tuple(v)) for k, v in PARTITION_RULES.items())

    def shard_geometry(self, bucket: Tuple[int, int, int],
                       row_bytes: int = 4,
                       feature_dim: int = 256) -> Tuple[dict, ...]:
        """Derived shard extents of a ``(B, H, W)`` bucket — the S5
        surface: each entry's ``extent`` must divide its mesh ``axis``
        or GSPMD pads the trailing shard (waste ``row_bytes`` per
        padded element row). The feature grid (H/8) is the one a
        boundary-even bucket can still break: H divisible by
        ``spatial`` does not imply H/8 is. ``feature_dim`` sizes a
        feature row's channels (the basic fnet's 256 by default — the
        dominant per-row tensor; a padded feature row is wasted across
        every channel, not one scalar per position)."""
        b, h, w = bucket
        return (
            {"name": f"batch {b}", "extent": b, "axis": "data",
             "row_bytes": h * w * 3 * row_bytes},
            {"name": f"image-height {h}", "extent": h, "axis": "spatial",
             "row_bytes": b * w * 3 * row_bytes},
            {"name": f"feature-height {h}//8", "extent": h // 8,
             "axis": "spatial",
             "row_bytes": b * (w // 8) * feature_dim * row_bytes},
        )


def mesh_model_config(config, mesh: Mesh):
    """The mesh-safe model config: with a >1 'data' axis the two-frame
    batch-concat encode would REDISTRIBUTE every row per step (XLA
    materializes the concat replicated and permutes the halves back —
    the first real graftshard S2 finding), so turn on
    ``split_encode`` (exact per sample: fnet is instance-norm).
    A 1-wide data axis keeps the bit-exact single-device path."""
    data = mesh.shape.get("data", 1)
    if data > 1 and not getattr(config, "split_encode", False):
        return dataclasses.replace(config, split_encode=True)
    return config
