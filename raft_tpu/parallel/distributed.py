"""Multi-host runtime: the distributed-backend the reference never had.

The reference's only parallelism is intra-process DataParallel — no process
groups, no launcher (SURVEY.md §2 "Distributed communication backend").
Here multi-host pods are first-class: ``initialize()`` wires the JAX
distributed runtime (ICI within a slice, DCN across slices), and the
global-batch helpers let each host feed only its shard while jit sees one
global array — the SPMD replacement for both NCCL transport and launchers.

Typical use (same code on every host):

    from raft_tpu.parallel import distributed as dist
    dist.initialize()                      # no-op on single host
    mesh = make_mesh()                     # all chips across all hosts
    batch = dist.host_local_batch(loader_batch, mesh)  # global jax.Arrays
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` with cloud-TPU auto-detection.

    Must run before anything touches a backend (so no jax.devices()/
    process_count() probing here — that would initialize XLA and doom the
    call). Explicit args or cluster-env presence make failures fatal;
    otherwise a failed auto-detect means single host and is a no-op, so
    entry points can call this unconditionally.
    """
    import os

    explicit = (coordinator_address is not None or num_processes is not None)
    cluster_env = any(os.environ.get(k) for k in (
        "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS"))
    try:
        if explicit:
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id)
        else:
            jax.distributed.initialize()
    except Exception:
        if explicit or cluster_env:
            raise
        return  # single host, nothing to wire


def process_batch_slice(global_batch: int) -> slice:
    """Which rows of the global batch this host should load."""
    per = global_batch // jax.process_count()
    start = jax.process_index() * per
    return slice(start, start + per)


def host_local_batch(batch: Dict[str, np.ndarray], mesh: Mesh
                     ) -> Dict[str, jax.Array]:
    """Host-local numpy shards -> global jax.Arrays on the mesh.

    Each host passes the rows from ``process_batch_slice``;
    ``make_array_from_process_local_data`` assembles the logically-global
    batch without any host ever holding it all — the DCN-side analog of
    the reference's per-GPU scatter (train.py:138), but across hosts.
    """
    from raft_tpu.parallel.mesh import validate_batch_extent

    # same conv-halo fence as the single-process shard_batch path: the
    # spatial axis is intra-process, so the local H *is* the global H
    # being sharded
    validate_batch_extent(batch, mesh)

    out: Dict[str, jax.Array] = {}
    for k, v in batch.items():
        if v.ndim == 4:
            spec = P("data", "spatial", None, None)
        elif v.ndim == 3:
            spec = P("data", "spatial", None)
        else:
            spec = P()
        sharding = NamedSharding(mesh, spec)
        out[k] = jax.make_array_from_process_local_data(sharding, v)
    return out
