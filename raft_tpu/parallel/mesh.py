"""Device mesh + sharding layout for SPMD training (TPU-first).

The reference's only parallelism is single-process ``nn.DataParallel``
(train.py:138) — batch scatter over CUDA peers. The TPU-native replacement:
a ``jax.sharding.Mesh`` with two axes:

- ``data``: batch-dim sharding (the DataParallel analog). Gradient reduction
  is inserted by XLA SPMD as ``psum`` over ICI — no NCCL, no process groups.
- ``spatial``: image-height sharding — the 2D analog of sequence/context
  parallelism. Convs get halo exchanges, the all-pairs correlation shards
  its query dimension (each chip owns its rows of the (HW)² volume) and
  XLA all-gathers fmap2 keys — the blockwise/ring-attention layout for
  resolutions that exceed one chip's HBM (SURVEY.md §5 long-context).

Multi-host: ``jax.distributed.initialize`` + per-host data loading make the
same code span pods, with DCN between slices (replaces the reference's
absent launcher).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None, spatial: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Mesh of shape (data = n/spatial, spatial)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    assert n % spatial == 0, (n, spatial)
    arr = np.asarray(devices).reshape(n // spatial, spatial)
    return Mesh(arr, ("data", "spatial"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Images/flow (B, H, W, C): batch over 'data', height over 'spatial'."""
    return NamedSharding(mesh, P("data", "spatial", None, None))


def valid_sharding(mesh: Mesh) -> NamedSharding:
    """valid mask (B, H, W)."""
    return NamedSharding(mesh, P("data", "spatial", None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    """Device-put a host batch dict onto the mesh with train shardings."""
    out = {}
    for k, v in batch.items():
        if v.ndim == 4:
            out[k] = jax.device_put(v, batch_sharding(mesh))
        elif v.ndim == 3:
            out[k] = jax.device_put(v, valid_sharding(mesh))
        else:
            out[k] = jax.device_put(v, replicated(mesh))
    return out
