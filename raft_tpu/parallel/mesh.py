"""Device mesh + sharding layout for SPMD training (TPU-first).

The reference's only parallelism is single-process ``nn.DataParallel``
(train.py:138) — batch scatter over CUDA peers. The TPU-native replacement:
a ``jax.sharding.Mesh`` with two axes:

- ``data``: batch-dim sharding (the DataParallel analog). Gradient reduction
  is inserted by XLA SPMD as ``psum`` over ICI — no NCCL, no process groups.
- ``spatial``: image-height sharding — the 2D analog of sequence/context
  parallelism. Convs get halo exchanges, the all-pairs correlation shards
  its query dimension (each chip owns its rows of the (HW)² volume) and
  XLA all-gathers fmap2 keys — the blockwise/ring-attention layout for
  resolutions that exceed one chip's HBM (SURVEY.md §5 long-context).

Multi-host: ``jax.distributed.initialize`` + per-host data loading make the
same code span pods, with DCN between slices (replaces the reference's
absent launcher).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# XLA SPMD miscompiles convolutions inside a ``lax.scan`` body when the
# conv's halo (kernel//2) reaches the per-shard extent of the sharded
# height dim: with shard_rows <= halo the in-loop halo exchange returns
# wrong rows (empirically: a scanned 7x7 conv over 2- or 3-row shards
# diverges by O(1e3) from the unsharded run, while 4-row shards are exact
# to 4e-4 in both forward and grad; the same conv OUTSIDE scan is exact at
# every extent). RAFT's largest feature-resolution kernel is the 7x7
# motion-encoder conv (halo 3) inside the scanned refinement loop, so
# spatial sharding requires strictly more than MAX_FEATURE_HALO feature
# rows (H/8) per shard.
MAX_FEATURE_HALO = 3


def validate_spatial_extent(image_h: int, mesh: Mesh) -> None:
    """Reject spatial shardings XLA cannot execute correctly (see above)."""
    spatial = dict(zip(mesh.axis_names, mesh.devices.shape)).get("spatial", 1)
    if spatial <= 1:
        return
    h_feat = image_h // 8
    if h_feat % spatial != 0:
        # Uneven feature-row sharding makes GSPMD pad the trailing shard;
        # the miscompile above was only characterized for even division, so
        # refuse rather than risk padded-shard halo behavior in-scan.
        raise ValueError(
            f"spatial={spatial} does not evenly divide the feature height "
            f"{h_feat} (= H{image_h}//8); uneven spatial shards are "
            f"unvalidated against the in-scan conv-halo miscompile — pick "
            f"H with H/8 divisible by the 'spatial' axis.")
    if (h_feat // spatial) <= MAX_FEATURE_HALO:
        raise ValueError(
            f"spatial={spatial} sharding of H={image_h} images gives "
            f"{h_feat // spatial} feature rows per shard; the scanned update "
            f"block's 7x7 conv (halo {MAX_FEATURE_HALO}) needs > "
            f"{MAX_FEATURE_HALO} rows per shard — use taller images or a "
            f"smaller 'spatial' axis.")


def make_mesh(n_devices: Optional[int] = None, spatial: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Mesh of shape (data = n/spatial, spatial)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    assert n % spatial == 0, (n, spatial)
    arr = np.asarray(devices).reshape(n // spatial, spatial)
    return Mesh(arr, ("data", "spatial"))


#: logical boundary values -> mesh axes per dim (None = unsharded) —
#: the ONE spec table every sharding consumer reads: the helpers below
#: (trainer/shard_batch), `partitioner.Partitioner` (the engine's pjit
#: seam), and the tools/graftshard audit (which checks this exact
#: table against the mesh a deployment builds — S4). ``frames``:
#: (B, H, W, 3) pixels — batch over 'data', image height over
#: 'spatial'. ``flow_init``/``flow``: the 1/8-res recurrence state and
#: full-res flow ride the same axes. ``valid``: (B, H, W) masks.
#: ``weights``: replicated by design — every device runs the whole net
#: over its batch rows (FSDP-style sharded state is a ROADMAP item).
PARTITION_RULES = {
    "frames": ("data", "spatial", None, None),
    "flow_init": ("data", "spatial", None, None),
    "flow": ("data", "spatial", None, None),
    "valid": ("data", "spatial", None),
    "weights": (),
}


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Images/flow (B, H, W, C): batch over 'data', height over 'spatial'."""
    return NamedSharding(mesh, P(*PARTITION_RULES["frames"]))


def valid_sharding(mesh: Mesh) -> NamedSharding:
    """valid mask (B, H, W)."""
    return NamedSharding(mesh, P(*PARTITION_RULES["valid"]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(*PARTITION_RULES["weights"]))


def validate_batch_extent(batch: dict, mesh: Mesh) -> None:
    """Apply the conv-halo spatial fence to a batch dict (first image
    tensor decides — all 4-d entries share H). One definition for every
    batch-sharding entry path (shard_batch here, host_local_batch on the
    multi-host side) so the fence cannot drift between them."""
    for v in batch.values():
        if v.ndim == 4:
            validate_spatial_extent(v.shape[1], mesh)
            break


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    """Device-put a host batch dict onto the mesh with train shardings."""
    validate_batch_extent(batch, mesh)
    out = {}
    for k, v in batch.items():
        if v.ndim == 4:
            out[k] = jax.device_put(v, batch_sharding(mesh))
        elif v.ndim == 3:
            out[k] = jax.device_put(v, valid_sharding(mesh))
        else:
            out[k] = jax.device_put(v, replicated(mesh))
    return out
