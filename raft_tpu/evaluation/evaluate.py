"""Validators + leaderboard submission writers.

Equivalent of ``/root/reference/evaluate.py`` with identical metric math:
EPE, 1/3/5px inlier rates (evaluate.py:118-124), KITTI F1-all = mean over
valid pixels of (epe > 3 ∧ epe/‖gt‖ > 0.05) (evaluate.py:148-163), and the
Sintel warm-start submission via host-side forward interpolation
(evaluate.py:22-50, core/utils/utils.py:26-54).

Because the reference's fork returns a single tensor in test mode and
thereby breaks these very callers (core/raft.py:141-143 — see SURVEY.md),
our model restores the upstream ``(flow_low, flow_up)`` contract and
everything here uses it.
"""

from __future__ import annotations

import os
import os.path as osp
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import ITERS_EVAL, RAFTConfig
from raft_tpu.data import datasets as ds
from raft_tpu.data import frame_utils
from raft_tpu.models import RAFT
from raft_tpu.ops.interp import forward_interpolate
from raft_tpu.ops.padding import InputPadder


def make_forward(config: RAFTConfig, iters: int):
    """Jitted test-mode forward: (variables, img1, img2[, flow_init])."""
    model = RAFT(config)

    @partial(jax.jit, static_argnames=())
    def fwd(variables, image1, image2):
        return model.apply(variables, image1, image2, iters=iters,
                           test_mode=True)

    @partial(jax.jit, static_argnames=())
    def fwd_init(variables, image1, image2, flow_init):
        return model.apply(variables, image1, image2, iters=iters,
                           test_mode=True, flow_init=flow_init)

    return fwd, fwd_init


def _to_device_pair(img1: np.ndarray, img2: np.ndarray, mode: str,
                    bucket: Optional[int] = None):
    """numpy HWC uint8/float -> padded (1,H,W,3) device arrays + padder.

    ``bucket`` additionally edge-pads H/W up to the next multiple, so
    datasets with a handful of distinct sizes (KITTI: ~5) share ONE jit
    specialization instead of recompiling per shape — the engine's
    bucket-routing trick (serving/engine.py:94-104) applied to eval.
    Returns ``(i1, i2, padder, crop_hw)``; crop model output to ``crop_hw``
    before ``padder.unpad``. Bucketing pads with replicated edges beyond
    the reference's ÷8 pad. Measured at trained weights on a 375x1242
    KITTI-shaped pair (tests/test_evaluation.py bucketing-delta test):
    the dataset EPE metric moves < 1e-2 px, but pointwise flow can move
    by a few px ANYWHERE in the frame — the fill shifts the encoders'
    instance-norm statistics, which couple every pixel to the fill
    content — so pass ``bucket=None`` for bit-matched parity runs; keep
    bucketing for throughput eval where the metric is the product.
    """
    i1 = jnp.asarray(img1, jnp.float32)[None]
    i2 = jnp.asarray(img2, jnp.float32)[None]
    padder = InputPadder(i1.shape, mode=mode)
    i1, i2 = padder.pad(i1, i2)
    hp, wp = i1.shape[1], i1.shape[2]
    if bucket:
        hb = -(-hp // bucket) * bucket
        wb = -(-wp // bucket) * bucket
        if (hb, wb) != (hp, wp):
            ext = ((0, 0), (0, hb - hp), (0, wb - wp), (0, 0))
            i1 = jnp.pad(i1, ext, mode="edge")
            i2 = jnp.pad(i2, ext, mode="edge")
    return i1, i2, padder, (hp, wp)


def _crop(flow: jax.Array, crop_hw) -> jax.Array:
    """Undo bucket fill on a (B, H, W, C) output (no-op when unbucketed)."""
    return flow[:, :crop_hw[0], :crop_hw[1], :]


def _batched_eval(val, fwd, variables, mode: str, batch_size: int):
    """Iterate a uniform-size dataset in padded device batches.

    Yields ``(flow_pred, flow_gt)`` per image, with predictions computed
    ``batch_size`` pairs at a time — one compile, a fraction of the
    dispatches of the reference's batch-1 loop (evaluate.py:104-116).
    The trailing partial batch is padded by repeating its last sample (same
    compiled shape; extra outputs dropped), so exactly one executable
    serves the whole dataset. Metric math is untouched: EPE is still
    computed per image downstream.
    """
    n = len(val)
    for start in range(0, n, batch_size):
        items = [val[i] for i in range(start, min(start + batch_size, n))]
        count = len(items)
        while len(items) < batch_size:  # repeat-pad the trailing batch
            items.append(items[-1])
        img1 = np.stack([it[0] for it in items]).astype(np.float32)
        img2 = np.stack([it[1] for it in items]).astype(np.float32)
        padder = InputPadder(img1.shape, mode=mode)
        i1, i2 = padder.pad(jnp.asarray(img1), jnp.asarray(img2))
        _, flow_pr = fwd(variables, i1, i2)
        flow = np.asarray(padder.unpad(flow_pr))
        for j in range(count):
            yield flow[j], items[j][2]


# FileNotFoundError on an unstaged dataset dir — the type
# trainer.run_validation catches to skip cleanly
_require_data = ds.require_nonempty


def validate_chairs(variables, config: RAFTConfig,
                    iters: int = ITERS_EVAL["chairs"],
                    data_root: str = "datasets",
                    batch_size: int = 4) -> Dict[str, float]:
    """FlyingChairs validation split EPE (evaluate.py:75-92)."""
    fwd, _ = make_forward(config, iters)
    val = ds.FlyingChairs(split="validation",
                          root=osp.join(data_root, "FlyingChairs_release/data"))
    _require_data(val, "FlyingChairs validation",
                  osp.join(data_root, "FlyingChairs_release/data"))
    epe_list = []
    for flow, flow_gt in _batched_eval(val, fwd, variables, "sintel",
                                       batch_size):
        epe = np.sqrt(np.sum((flow - flow_gt) ** 2, -1))
        epe_list.append(epe.reshape(-1))
    epe = float(np.mean(np.concatenate(epe_list)))
    print(f"Validation Chairs EPE: {epe:f}")
    return {"chairs": epe}


def validate_sintel(variables, config: RAFTConfig,
                    iters: int = ITERS_EVAL["sintel"],
                    data_root: str = "datasets",
                    batch_size: int = 4) -> Dict[str, float]:
    """Sintel train-split validation (evaluate.py:96-127)."""
    fwd, _ = make_forward(config, iters)
    results = {}
    for dstype in ["clean", "final"]:
        val = ds.MpiSintel(split="training", root=osp.join(data_root, "Sintel"),
                           dstype=dstype)
        _require_data(val, f"Sintel training/{dstype}",
                      osp.join(data_root, "Sintel"))
        epe_list = []
        for flow, flow_gt in _batched_eval(val, fwd, variables, "sintel",
                                           batch_size):
            epe = np.sqrt(np.sum((flow - flow_gt) ** 2, -1))
            epe_list.append(epe.reshape(-1))

        epe_all = np.concatenate(epe_list)
        print("Validation (%s) EPE: %f, 1px: %f, 3px: %f, 5px: %f" % (
            dstype, np.mean(epe_all), np.mean(epe_all < 1),
            np.mean(epe_all < 3), np.mean(epe_all < 5)))
        # reference reports the mean of per-image means here (evaluate.py:125)
        results[dstype] = float(np.mean([e.mean() for e in epe_list]))
    return results


def validate_kitti(variables, config: RAFTConfig,
                   iters: int = ITERS_EVAL["kitti"],
                   data_root: str = "datasets",
                   shape_bucket: Optional[int] = 64) -> Dict[str, float]:
    """KITTI-15 train-split validation with F1-all (evaluate.py:131-166).

    KITTI frames come in a handful of near-identical sizes; ``shape_bucket``
    routes them through one padded shape so the jitted forward compiles
    once instead of per size (each remote TPU compile is minutes). Set
    ``shape_bucket=None`` for strict reference-parity padding.
    """
    fwd, _ = make_forward(config, iters)
    val = ds.KITTI(split="training", root=osp.join(data_root, "KITTI"))
    _require_data(val, "KITTI training", osp.join(data_root, "KITTI"))
    out_list, epe_list = [], []
    for i in range(len(val)):
        img1, img2, flow_gt, valid_gt = val[i]
        i1, i2, padder, crop_hw = _to_device_pair(img1, img2, "kitti",
                                                  bucket=shape_bucket)
        _, flow_pr = fwd(variables, i1, i2)
        flow = np.asarray(padder.unpad(_crop(flow_pr, crop_hw))[0])

        epe = np.sqrt(np.sum((flow - flow_gt) ** 2, -1)).reshape(-1)
        mag = np.sqrt(np.sum(flow_gt ** 2, -1)).reshape(-1)
        val_mask = valid_gt.reshape(-1) >= 0.5

        out = ((epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05)
               ).astype(np.float32)
        epe_list.append(epe[val_mask].mean())
        out_list.append(out[val_mask])

    epe = float(np.mean(np.array(epe_list)))
    f1 = float(100 * np.mean(np.concatenate(out_list)))
    print(f"Validation KITTI: {epe:f}, {f1:f}")
    return {"kitti-epe": epe, "kitti-f1": f1}


def create_sintel_submission(variables, config: RAFTConfig, iters: int = 32,
                             warm_start: bool = False,
                             output_path: str = "sintel_submission",
                             data_root: str = "datasets") -> None:
    """Sintel leaderboard writer with optional warm start (evaluate.py:22-50)."""
    fwd, fwd_init = make_forward(config, iters)
    for dstype in ["clean", "final"]:
        test = ds.MpiSintel(split="test", aug_params=None,
                            root=osp.join(data_root, "Sintel"), dstype=dstype)
        _require_data(test, f"Sintel test/{dstype}",
                      osp.join(data_root, "Sintel"))
        flow_prev, sequence_prev = None, None
        for test_id in range(len(test)):
            image1, image2, (sequence, frame) = test[test_id]
            if sequence != sequence_prev:
                flow_prev = None

            i1, i2, padder, _ = _to_device_pair(image1, image2, "sintel")
            if flow_prev is None:
                flow_low, flow_pr = fwd(variables, i1, i2)
            else:
                flow_low, flow_pr = fwd_init(variables, i1, i2,
                                             jnp.asarray(flow_prev)[None])
            flow = np.asarray(padder.unpad(flow_pr)[0])

            if warm_start:
                flow_prev = forward_interpolate(np.asarray(flow_low[0]))

            output_dir = osp.join(output_path, dstype, sequence)
            os.makedirs(output_dir, exist_ok=True)
            frame_utils.write_flow(
                osp.join(output_dir, "frame%04d.flo" % (frame + 1)), flow)
            sequence_prev = sequence


def create_kitti_submission(variables, config: RAFTConfig, iters: int = 24,
                            output_path: str = "kitti_submission",
                            data_root: str = "datasets",
                            shape_bucket: Optional[int] = None) -> None:
    """KITTI leaderboard writer (evaluate.py:53-71).

    ``shape_bucket`` defaults to OFF here (unlike ``validate_kitti``):
    submission flows are externally scored, so they get exact
    reference-parity padding unless the caller opts into bucketed compiles.
    """
    fwd, _ = make_forward(config, iters)
    test = ds.KITTI(split="testing", aug_params=None,
                    root=osp.join(data_root, "KITTI"))
    _require_data(test, "KITTI testing", osp.join(data_root, "KITTI"))
    os.makedirs(output_path, exist_ok=True)
    for test_id in range(len(test)):
        image1, image2, (frame_id,) = test[test_id]
        i1, i2, padder, crop_hw = _to_device_pair(image1, image2, "kitti",
                                                  bucket=shape_bucket)
        _, flow_pr = fwd(variables, i1, i2)
        flow = np.asarray(padder.unpad(_crop(flow_pr, crop_hw))[0])
        frame_utils.write_flow_kitti(osp.join(output_path, frame_id), flow)


VALIDATORS = {
    "chairs": validate_chairs,
    "sintel": validate_sintel,
    "kitti": validate_kitti,
}
