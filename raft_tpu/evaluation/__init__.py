from raft_tpu.evaluation.evaluate import (  # noqa: F401
    create_kitti_submission,
    create_sintel_submission,
    validate_chairs,
    validate_kitti,
    validate_sintel,
)
