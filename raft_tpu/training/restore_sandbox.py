"""Restore one Orbax step in a throwaway process; emit a clean snapshot.

Usage::

    python -m raft_tpu.training.restore_sandbox <step_dir> <out_msgpack>

Why a subprocess at all: a tensorstore read against a torn/corrupt
step leaves the reader process's heap poisoned even when the failure
surfaces as a clean python exception (use-after-free in the async
read machinery; glibc aborts strike minutes later at an
allocation-layout-dependent point — observed repeatedly under the
fault drills). A trainer that restores in-process therefore can't
recover from data-file damage mechanically: it quarantines, falls
back ... and then aborts anyway. Exiling every orbax read to a
process that exits right after makes corruption survivable by
construction: this child restores the step, re-serializes the tree as
an atomic flax-msgpack snapshot (tmp + fsync + rename plus SHA-256
sidecar, via ``tools.convert.save_converted``), and exits — if the
read poisoned anything, the poison dies here. Exit 0 with the
verified snapshot on disk is the only success signal; a torn/corrupt
step surfaces as a nonzero exit (or a crash), which
``restore_train_state`` turns into quarantine-and-fall-back.
"""

from __future__ import annotations

import os
import sys
import traceback

#: the step itself could not be restored (torn/corrupt/incompatible) —
#: the caller may quarantine it and fall back to an older step
STEP_UNREADABLE_EXIT = 4
#: the snapshot could not be written (disk full, permissions) — an
#: ENVIRONMENT failure: the step may be perfectly intact, and callers
#: must surface the error rather than quarantine good history over it
ENV_ERROR_EXIT = 5


def _state_dictify(tree):
    """Reshape orbax's raw restore tree into flax state-dict form so
    the trainer can map it straight onto its state template with
    ``serialization.from_bytes``: sequences become index-keyed dicts
    (how flax renders the optax tuple chain) and ``None`` — orbax's
    rendering of empty containers like ``optax.EmptyState`` — becomes
    the empty dict flax expects."""
    if isinstance(tree, (list, tuple)):
        return {str(i): _state_dictify(v) for i, v in enumerate(tree)}
    if isinstance(tree, dict):
        return {k: _state_dictify(v) for k, v in tree.items()}
    if tree is None:
        return {}
    return tree


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 2:
        print("usage: restore_sandbox <step_dir> <out_msgpack>",
              file=sys.stderr)
        return 2
    step_dir, out_path = argv
    # host-side re-serialization only: never dial a TPU for a restore
    os.environ["JAX_PLATFORMS"] = "cpu"
    from raft_tpu.utils.platform import respect_cpu_request
    respect_cpu_request()
    import orbax.checkpoint as ocp

    from raft_tpu.tools.convert import save_converted

    ckptr = ocp.StandardCheckpointer()
    try:
        try:
            # no target tree: the raw restore yields host arrays in the
            # saved structure; the trainer maps them back into its state
            # template with flax's from_bytes ("default" is the
            # CheckpointManager item name on the save side)
            tree = ckptr.restore(os.path.join(step_dir, "default"))
        except Exception:
            traceback.print_exc()
            return STEP_UNREADABLE_EXIT
    finally:
        ckptr.close()
    try:
        save_converted(_state_dictify(tree), out_path)
    except Exception:
        traceback.print_exc()
        return ENV_ERROR_EXIT
    return 0


if __name__ == "__main__":
    sys.exit(main())
