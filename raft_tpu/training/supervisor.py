"""Process-level supervision: relaunch training after wedge/preemption.

``HangWatch`` turns a half-up tunnel wedge into ``exit 3``
(WEDGED_EXIT_CODE) — but nothing in-repo ever restarted the run, so a
wedge still ended training and burned the rest of the window
(OUTAGE_r04/r05). The supervisor closes that loop: it launches the
training command as a child process and, on a retryable death (wedge,
preemption signal, simulated power loss), relaunches it after a
jittered exponential backoff — relying on ``--resume`` plus the
integrity-checked checkpoint stack to pick up from the newest intact
step.

Give-up rules (a supervisor must never hot-loop a deterministic crash):

- two consecutive CRASH-class failures (a plain nonzero exit) whose
  restore point (newest on-disk step) did not advance — the relaunch
  would replay the same step into the same crash. Wedges (exit 3) and
  signal deaths are documented-transient classes and never trip this
  rule (the OUTAGE_r04/r05 tunnel wedge can recur before the first
  checkpoint ever commits — that must burn restart budget, not be
  misread as deterministic), and a run with no restore point yet
  (probe None) has nothing to "replay";
- ``max_restarts`` exhausted;
- exit code 2 (usage error) is never retried.

``run()`` returns 0 on eventual success, the child's exit code on
give-up, or ``128 + signum`` when the final child died to a signal —
``sys.exit`` of a raw negative ``Popen`` code would be masked to a
meaningless ``256 - n`` status, breaking the exit-code table.

Operator stop: SIGTERM/SIGINT delivered to the *supervisor* pid are
forwarded to the current child, and the supervisor exits ``128 +
signum`` after the child dies instead of restarting it. Without this a
``kill <supervisor-pid>`` (or a process manager that signals only its
direct child, not the group) would take down the parent while the
reparented trainer keeps training — holding the accelerator claim and
racing any replacement launch on the same checkpoint dir.

Deliberately jax-free: the parent stays a tiny process a wedged backend
cannot take down, and the restore-point probe is a directory scan
(utils/ckpt_scan), not an Orbax open whose cached view would go stale
across children.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Optional, Sequence

from raft_tpu.testing.faults import CRASH_EXIT_CODE
from raft_tpu.utils.ckpt_scan import latest_step_on_disk
from raft_tpu.utils.retry import backoff_delays
from raft_tpu.utils.watchdog import WEDGED_EXIT_CODE

#: usage errors are deterministic; retrying an argparse failure is noise
NON_RETRYABLE_EXIT_CODES = (2,)

#: env var telling the child which supervision attempt it is (0-based);
#: testing.faults scopes drill plan entries to attempts through it
ATTEMPT_ENV = "RAFT_SUPERVISOR_ATTEMPT"

_NO_FAILURE = object()  # distinct from None: "no checkpoint on disk"

#: operator-stop signals the supervisor forwards to the child rather
#: than dying around; SIGINT is in the set for non-tty delivery (a tty
#: ^C already signals the whole foreground group — the forward is then
#: a harmless duplicate)
_FORWARD_SIGNALS = (signal.SIGTERM, signal.SIGINT)


def describe_exit(rc: int) -> str:
    if rc == WEDGED_EXIT_CODE:
        return f"child wedged (exit {rc}, no-progress watchdog)"
    if rc == CRASH_EXIT_CODE:
        return f"child crashed (exit {rc}, injected fault drill)"
    if rc < 0:
        try:
            name = signal.Signals(-rc).name
        except ValueError:
            name = str(-rc)
        return f"child killed by signal {name} (preemption?)"
    return f"child died (exit {rc})"


def exit_class(rc: int) -> str:
    """Coarse label for metrics/alerting: which failure mode was it."""
    if rc == WEDGED_EXIT_CODE:
        return "wedge"
    if rc == CRASH_EXIT_CODE:
        return "crash-drill"
    if rc < 0:
        return "signal"
    return "usage-error" if rc in NON_RETRYABLE_EXIT_CODES else "crash"


class Supervisor:
    """Run ``argv`` as a supervised child until clean exit or give-up.

    ``ckpt_dir`` (the stage dir) enables the restore-point probe behind
    the deterministic-crash rule; pass ``probe_step`` to override it,
    or neither to supervise on ``max_restarts`` alone. ``launch`` and
    ``sleep`` are injectable for tests.

    ``metrics_path``: optional ``metrics.jsonl`` the supervisor appends
    restart events to (attempt, exit class, restored step, backoff) —
    the alerting substrate: a dashboard tailing the trainer's Logger
    records sees the restarts interleaved with the training curves.
    Append-only JSON lines, the Logger's format; a failed append is
    logged and ignored (observability must never take down recovery).
    """

    def __init__(self, argv: Sequence[str], *, max_restarts: int = 5,
                 ckpt_dir: Optional[str] = None,
                 probe_step: Optional[Callable[[], Optional[int]]] = None,
                 base_s: float = 1.0, max_s: float = 60.0,
                 jitter: float = 0.5, rng=None,
                 launch: Optional[Callable[[int, dict], int]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 metrics_path: Optional[str] = None):
        self.argv = list(argv)
        self.max_restarts = int(max_restarts)
        if probe_step is None and ckpt_dir is not None:
            probe_step = lambda: latest_step_on_disk(ckpt_dir)  # noqa: E731
        self._probe = probe_step
        self._delays = backoff_delays(base_s, max_s, jitter=jitter, rng=rng)
        self._launch = launch if launch is not None else self._spawn
        self._sleep = sleep
        self.restarts = 0
        self._child: Optional[subprocess.Popen] = None
        self._stop_signal: Optional[int] = None
        self._metrics_path = metrics_path

    def _spawn(self, attempt: int, env: dict) -> int:
        proc = subprocess.Popen(self.argv, env=env)
        self._child = proc
        # a stop can land between the loop-top check and the handle
        # assignment above — the handler saw _child=None and had
        # nothing to forward to. Re-check now that the child is
        # visible, or the fresh child would run a full stage inside
        # proc.wait() before the stop took effect
        if self._stop_signal is not None and proc.poll() is None:
            proc.send_signal(self._stop_signal)
        try:
            return proc.wait()
        finally:
            self._child = None

    def _on_signal(self, signum, frame) -> None:
        """SIGTERM/SIGINT handler: forward to the child and remember
        the stop so the wait loop exits instead of restarting."""
        self._stop_signal = signum
        child = self._child
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    def _log(self, msg: str) -> None:
        print(f"[supervisor] {msg}", file=sys.stderr, flush=True)

    def _record(self, event: str, **fields) -> None:
        """Append one event record to metrics.jsonl (Logger format)."""
        if self._metrics_path is None:
            return
        rec = {"event": event, "time": time.time(), **fields}
        try:
            parent = os.path.dirname(self._metrics_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self._metrics_path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(rec) + "\n")
        except OSError as exc:
            self._log(f"metrics append failed ({exc}) — continuing")

    @staticmethod
    def _exit_code(rc: int) -> int:
        """Map a child's raw ``Popen`` code to a sys.exit-able status:
        negative (signal death) becomes the shell's ``128 + signum``
        convention — ``sys.exit(-9)`` would be masked to an undocumented
        247 that matches nothing in the README exit-code table."""
        return 128 - rc if rc < 0 else rc

    def run(self) -> int:
        """Supervise; returns 0 on eventual success, ``128 + signum``
        on an operator stop (SIGTERM/SIGINT forwarded to the child),
        else the final child's exit status via :meth:`_exit_code`
        (callers ``sys.exit`` it — one failure mode, one code, per
        exit-code discipline)."""
        installed = {}
        try:
            for s in _FORWARD_SIGNALS:
                installed[s] = signal.signal(s, self._on_signal)
        except ValueError:
            pass  # not the main thread (embedded/tests): no handlers
        try:
            return self._supervise()
        finally:
            for s, prev in installed.items():
                signal.signal(s, prev)

    def _stopped(self, what: str) -> int:
        name = signal.Signals(self._stop_signal).name
        self._log(f"{name} received — {what}, not restarting")
        return 128 + self._stop_signal

    def _supervise(self) -> int:
        prev_fail_step = _NO_FAILURE
        while True:
            # a stop that landed with no child alive (during backoff,
            # or before the first spawn) had nothing to forward to —
            # honoring it only after one more FULL child run would
            # leave a trainer the operator already killed holding the
            # accelerator claim for hours
            if self._stop_signal is not None:
                return self._stopped("stop requested with no child "
                                     "running")
            env = dict(os.environ)
            env[ATTEMPT_ENV] = str(self.restarts)
            rc = self._launch(self.restarts, env)
            if self._stop_signal is not None:
                outcome = describe_exit(rc) if rc else "child exited clean"
                return self._stopped(f"forwarded to child ({outcome})")
            if rc == 0:
                if self.restarts:
                    self._log(f"child exited clean after "
                              f"{self.restarts} restart(s)")
                    self._record("supervisor_recovered",
                                 restarts=self.restarts)
                return 0
            why = describe_exit(rc)
            if rc in NON_RETRYABLE_EXIT_CODES:
                self._log(f"{why} — usage error, not retrying")
                self._record("supervisor_give_up", reason="usage-error",
                             exit_code=rc, attempt=self.restarts)
                return self._exit_code(rc)
            fail_step = self._probe() if self._probe is not None else None
            # the deterministic-crash rule judges CRASH-class exits
            # only: wedges and signal deaths are transient by
            # definition (and recur at the same step when they strike
            # faster than the checkpoint cadence), and a None probe
            # (no checkpoint yet) has nothing to deterministically
            # replay — both must spend restart budget instead
            crash_class = rc > 0 and rc != WEDGED_EXIT_CODE
            if (crash_class and self._probe is not None
                    and fail_step is not None
                    and prev_fail_step is not _NO_FAILURE
                    and fail_step == prev_fail_step):
                self._log(
                    f"{why} with the restore point still at step "
                    f"{fail_step} — same failure twice with no progress "
                    "is a deterministic crash, giving up")
                self._record("supervisor_give_up",
                             reason="deterministic-crash", exit_code=rc,
                             attempt=self.restarts,
                             restored_step=fail_step)
                return self._exit_code(rc)
            prev_fail_step = fail_step if crash_class else _NO_FAILURE
            if self.restarts >= self.max_restarts:
                self._log(f"{why} — max_restarts={self.max_restarts} "
                          "exhausted, giving up")
                self._record("supervisor_give_up",
                             reason="max-restarts", exit_code=rc,
                             attempt=self.restarts,
                             restored_step=fail_step)
                return self._exit_code(rc)
            self.restarts += 1
            delay = next(self._delays)
            self._log(f"{why} — restart {self.restarts}/"
                      f"{self.max_restarts} (resume point: step "
                      f"{fail_step}) in {delay:.1f}s")
            self._record("supervisor_restart", attempt=self.restarts,
                         exit_code=rc, exit_class=exit_class(rc),
                         restored_step=fail_step,
                         backoff_s=round(delay, 3))
            # sliced so a stop signal cuts the backoff short (PEP 475
            # would otherwise resume a single long sleep to completion
            # and relaunch); the loop-top check turns it into an exit
            remaining = delay
            while remaining > 0 and self._stop_signal is None:
                chunk = min(remaining, 0.5)
                self._sleep(chunk)
                remaining -= chunk
