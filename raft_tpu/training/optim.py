"""Optimizer factory: AdamW + linear OneCycle + global-norm clip.

Replicates ``fetch_optimizer`` (train.py:79-86): AdamW(lr, wdecay, eps) with
OneCycleLR(total=num_steps+100, pct_start=0.05, anneal='linear') and
clip_grad_norm(1.0) applied before the step (train.py:176-177).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def onecycle_linear_schedule(peak_lr: float, total_steps: int,
                             pct_start: float = 0.05,
                             div_factor: float = 25.0,
                             final_div_factor: float = 1e4):
    """torch OneCycleLR with anneal_strategy='linear'.

    Phase 1 (first ``pct_start`` of steps): linear ``peak/div_factor`` → peak.
    Phase 2: linear peak → ``initial/final_div_factor``.
    """
    initial = peak_lr / div_factor
    final = initial / final_div_factor
    # torch's phase boundaries: warm-up ends at pct_start*total - 1 and the
    # anneal reaches `final` exactly at step total - 1 (lr_scheduler.py's
    # _schedule_phases) — the off-by-ones matter for short schedules
    warm_end = max(pct_start * total_steps - 1.0, 1.0)
    down_len = max(total_steps - 1.0 - warm_end, 1.0)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up = initial + (peak_lr - initial) * (step / warm_end)
        frac = jnp.clip((step - warm_end) / down_len, 0.0, 1.0)
        down = peak_lr + (final - peak_lr) * frac
        return jnp.where(step <= warm_end, jnp.minimum(up, peak_lr), down)

    return schedule


def make_optimizer(lr: float, num_steps: int, wdecay: float = 1e-4,
                   epsilon: float = 1e-8, clip: float = 1.0):
    """AdamW + OneCycle + clip, matching the reference trainer."""
    schedule = onecycle_linear_schedule(lr, num_steps + 100)
    tx = optax.chain(
        optax.clip_by_global_norm(clip),
        optax.adamw(schedule, b1=0.9, b2=0.999, eps=epsilon,
                    weight_decay=wdecay),
    )
    return tx, schedule
