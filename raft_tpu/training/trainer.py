"""The training loop: SPMD train step + data pipeline + logging + ckpt.

The ``train(args)`` analog (train.py:136-214), TPU-first:

- one jit-compiled step over a device ``Mesh`` (batch sharded on the data
  axis, params replicated) instead of ``nn.DataParallel`` (train.py:138);
  XLA inserts the gradient psum over ICI;
- bf16 mixed precision by policy — no GradScaler, fp32 islands live inside
  the model (core/raft.py:102-103 analog);
- full-train-state Orbax checkpoints every ``val_freq`` steps plus
  weights-only msgpack finals mirroring ``checkpoints/<name>.pth``
  (train.py:185-187, 211-212);
- validation every ``val_freq`` with the reference metric names
  (train.py:189-198).

Restore semantics: ``restore_ckpt`` loads weights only with the reference's
``strict=False`` spirit (train.py:141-142) — the LR schedule restarts, which
the curriculum depends on; ``resume=True`` restores the FULL state (the
capability upgrade).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import RAFTConfig, TrainConfig
from raft_tpu.parallel.mesh import make_mesh, replicated, shard_batch
from raft_tpu.parallel.partitioner import mesh_model_config
from raft_tpu.testing import faults
from raft_tpu.training import checkpoint as ckpt_lib
from raft_tpu.training.logger import Logger
from raft_tpu.training.optim import onecycle_linear_schedule
from raft_tpu.utils.ckpt_scan import latest_step_on_disk
from raft_tpu.training.train_step import (RAFTTrainState, create_train_state,
                                          make_train_step)


def load_weights(path: str, config: RAFTConfig) -> Dict:
    """Load weights from a reference ``.pth`` or converted msgpack."""
    from raft_tpu.tools import convert

    if path.endswith(".pth"):
        return convert.load_pth(path, config)
    return convert.load_converted(path, config)


def run_validation(variables, model_cfg: RAFTConfig, names,
                   data_root: str) -> Dict[str, float]:
    from raft_tpu.evaluation import evaluate as ev

    results: Dict[str, float] = {}
    for name in names:
        try:
            if name == "chairs":
                results.update(ev.validate_chairs(
                    variables, model_cfg, data_root=data_root))
            elif name == "sintel":
                results.update(ev.validate_sintel(
                    variables, model_cfg, data_root=data_root))
            elif name == "kitti":
                results.update(ev.validate_kitti(
                    variables, model_cfg, data_root=data_root))
        except FileNotFoundError as e:
            print(f"validation '{name}' skipped: {e}", flush=True)
    return results


def train(model_cfg: RAFTConfig, train_cfg: TrainConfig,
          resume: bool = False, loader=None) -> RAFTTrainState:
    """Run one curriculum stage; returns the final state."""
    np.random.seed(train_cfg.seed)  # train.py:241-242
    rng = jax.random.PRNGKey(train_cfg.seed)

    stage_dir = os.path.join(train_cfg.checkpoint_dir, train_cfg.name,
                             train_cfg.stage)
    os.makedirs(stage_dir, exist_ok=True)

    init_variables = None
    if train_cfg.restore_ckpt:
        init_variables = load_weights(train_cfg.restore_ckpt, model_cfg)
    state = create_train_state(model_cfg, train_cfg, rng,
                               image_hw=train_cfg.image_size,
                               init_variables=init_variables)
    # filesystem-truth probe (not ckpt_lib.latest_step): answering
    # "does any step exist" must not spin up a cached CheckpointManager
    # that restore_train_state's quarantine path would then have to
    # tear down before it can rename a bad step dir
    if resume and latest_step_on_disk(stage_dir) is not None:
        state = ckpt_lib.restore_train_state(stage_dir, state)
        print(f"resumed from step {int(state.step)}", flush=True)

    if loader is None:
        from raft_tpu.data.loader import fetch_dataloader
        loader = fetch_dataloader(
            train_cfg.stage, train_cfg.image_size, train_cfg.batch_size,
            data_root=train_cfg.data_root, num_workers=train_cfg.num_workers,
            seed=train_cfg.seed, wire_dtype="uint8",
            on_bad_sample=train_cfg.on_bad_sample, stall_s=train_cfg.stall_s)

    mesh = make_mesh()
    # mesh-safe encoder path on a >1 'data' axis (weights identical; the
    # batch-concat encode would redistribute every row per step — see
    # RAFTConfig.split_encode / graftshard S2)
    model_cfg = mesh_model_config(model_cfg, mesh)
    step_fn = jax.jit(make_train_step(model_cfg, train_cfg),
                      donate_argnums=(0,))
    schedule = onecycle_linear_schedule(train_cfg.lr, train_cfg.num_steps + 100)
    logger = Logger(os.path.join(train_cfg.log_dir, train_cfg.name),
                    train_cfg.sum_freq, lr_fn=schedule)
    logger.start_at(int(state.step))
    # half-up tunnel fence: a wedged backend blocks dispatch/fetch with
    # nothing to catch; exit code 3 lets runbooks re-probe instead of
    # sleeping out their timeout (see utils/watchdog and
    # TrainConfig.hang_s)
    from raft_tpu.utils.watchdog import HangWatch
    hang_watch = HangWatch(train_cfg.hang_s, label="train loop")
    hang_watch.start()

    # try/finally: the armed daemon must not outlive train() on the
    # exception path (data error, OOM, KeyboardInterrupt) — an
    # in-process caller that catches the exception would otherwise be
    # hard-killed by os._exit(WEDGED_EXIT_CODE) once hang_s elapses
    # with no beats (ADVICE.md round 5)
    try:
        with mesh:
            state = jax.device_put(state, replicated(mesh))
            # the base key is a boundary value of every step too:
            # declare it replicated instead of leaving XLA to resolve
            # an unconstrained host array (graftshard S4 discipline)
            rng = jax.device_put(rng, replicated(mesh))
            total_steps = int(state.step)
            keep_training = total_steps < train_cfg.num_steps
            prof = train_cfg.profile_steps
            profiling = False
            # Metrics accumulate ON DEVICE and are fetched once per
            # sum_freq window: fetching per-step scalars costs one D2H
            # round trip per step, which on a remote backend caps the
            # loop at ~1/RTT steps/s (measured 0.72 steps/s against a
            # ~3 steps/s device, session C).
            metric_sums = None
            acc_steps = 0
            acc_fn = jax.jit(
                lambda acc, m: jax.tree_util.tree_map(jnp.add, acc, m),
                donate_argnums=(0,))

            def flush_metrics():
                nonlocal metric_sums, acc_steps
                if acc_steps:
                    sums = jax.device_get(metric_sums)
                    # the fetch above is a real D2H round trip — proof
                    # of COMPLETED device work, unlike the async
                    # dispatch return of step_fn — so it is the honest
                    # heartbeat: a mid-train wedge stops flushes and
                    # the watchdog fires within hang_s
                    hang_watch.beat()
                    logger.push_sums(
                        {k: float(v) for k, v in sums.items()
                         if k in ("loss", "epe", "1px", "3px", "5px")},
                        acc_steps)
                    metric_sums, acc_steps = None, 0

            def device_batches(host_loader, depth=2):
                """shard_batch runs ``depth`` batches ahead of
                consumption: jax transfers are async, so H2D of batch
                N+1 overlaps the device compute of batch N instead of
                serializing with it."""
                from collections import deque

                buf = deque()
                for host_batch in host_loader:
                    buf.append(shard_batch(host_batch, mesh))
                    if len(buf) >= depth:
                        yield buf.popleft()
                while buf:
                    yield buf.popleft()

            while keep_training:
                for sharded in device_batches(loader):
                    # crash-safety drill site: a "hang" here is what a
                    # half-up backend looks like (no beats -> watchdog
                    # exit 3), a "crash" is preemption mid-step; no-op
                    # one None-check when no plan is armed
                    faults.fault_point("trainer.step")
                    if (prof and not profiling
                            and prof[0] <= total_steps < prof[1]):
                        jax.profiler.start_trace(
                            os.path.join(train_cfg.log_dir,
                                         train_cfg.name))
                        profiling = True
                    # constant base key: the step fold_ins state.step
                    # itself (a host-side split here cost ~730 ms/step
                    # of pipelining on the remote tunnel —
                    # BENCH_NOTES.md round 5)
                    state, metrics = step_fn(state, sharded, rng)
                    if profiling and total_steps >= prof[1]:
                        jax.block_until_ready(metrics)
                        jax.profiler.stop_trace()
                        profiling = False
                    metric_sums = (metrics if metric_sums is None
                                   else acc_fn(metric_sums, metrics))
                    acc_steps += 1
                    total_steps += 1
                    # reference cadence (train.py:97-103): record/print
                    # at steps sum_freq-1, 2*sum_freq-1, ... so
                    # metrics.jsonl stays step-aligned across versions
                    if (total_steps % train_cfg.sum_freq
                            == train_cfg.sum_freq - 1):
                        flush_metrics()

                    if (total_steps % train_cfg.val_freq
                            == train_cfg.val_freq - 1):
                        flush_metrics()  # window record precedes val
                        ckpt_lib.save_train_state(stage_dir, state)
                        # <step+1>_<name>.pth analog (train.py:185-187)
                        weights_path = os.path.join(
                            train_cfg.checkpoint_dir,
                            f"{total_steps + 1}_{train_cfg.name}"
                            ".msgpack")
                        ckpt_lib.save_weights(
                            weights_path,
                            jax.device_get(
                                ckpt_lib.variables_from_state(state)))
                        results = run_validation(
                            ckpt_lib.variables_from_state(state),
                            model_cfg, train_cfg.validation,
                            train_cfg.data_root)
                        if results:
                            logger.write_dict(results)
                        hang_watch.beat()  # long validation ≠ wedge

                    if total_steps >= train_cfg.num_steps:
                        keep_training = False
                        break
            flush_metrics()
            if profiling:
                jax.block_until_ready(state.params)
                jax.profiler.stop_trace()

        final_path = os.path.join(train_cfg.checkpoint_dir,
                                  f"{train_cfg.name}.msgpack")
        ckpt_lib.save_weights(
            final_path,
            jax.device_get(ckpt_lib.variables_from_state(state)))
        print(f"saved final weights to {final_path}", flush=True)
    finally:
        # the flush below gets its own full hang_s window — staleness
        # is otherwise counted from the last metric flush, and a
        # legitimate end-of-run Orbax wait near the window's edge
        # would be hard-killed as "wedged"
        hang_watch.beat()
        try:
            # flush pending async Orbax saves on EVERY path — an
            # exception after a val-boundary save otherwise exits with
            # a partially-written checkpoint that a resume later loads.
            # The watchdog stays armed through this: a wedged flush
            # must still become exit-3, not a silent hang.
            ckpt_lib.close_all()
        finally:
            # stop() is a bare Event.set and cannot raise; it runs
            # even when close_all does — in-process callers must not
            # inherit the daemon on ANY path
            hang_watch.stop()
            logger.close()
    return state


def _final_intact(final: str) -> bool:
    """Gate for the skip-completed-stage shortcut: bare existence of a
    stage's final ``.msgpack`` is not proof it is loadable — post-save
    bit rot (or a stale sidecar from an interrupted save) produces a
    file the NEXT stage's ``load_weights`` rejects at startup, before
    any checkpoint advances, which the supervisor then reads as a
    deterministic crash and gives up on: the curriculum is permanently
    wedged until someone deletes the file by hand. Verify the manifest
    up front instead; a failing final is quarantined aside (with its
    sidecar) so the stage retrains and atomically rewrites it. A
    missing sidecar passes, matching ``verify_manifest``'s
    pre-hardening compatibility — the rename in ``save_converted`` is
    atomic, so a final without a manifest is still a complete file."""
    from raft_tpu.tools.convert import (CorruptCheckpointError,
                                        manifest_path, verify_manifest)
    from raft_tpu.utils.ckpt_scan import quarantine_path

    try:
        with open(final, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return False  # racing delete: nothing to quarantine, retrain
    # any other OSError (EIO, EACCES — a flaky mount, not the file)
    # propagates: an environmental read failure is not evidence
    # against the artifact and must not feed the quarantine path,
    # same rule as checkpoint.py's StepDamagedError gating
    try:
        verify_manifest(final, data)
        return True
    except CorruptCheckpointError as exc:
        dst = quarantine_path(final)
        try:
            os.rename(final, dst)
            if os.path.exists(manifest_path(final)):
                os.rename(manifest_path(final), manifest_path(dst))
        except OSError:
            pass  # vanished mid-quarantine: retraining overwrites it
        print(f"existing final at {final} fails its integrity check "
              f"({type(exc).__name__}: {exc}) — quarantined to {dst}; "
              "retraining the stage", flush=True)
        return False


def train_curriculum(stages, model_cfg: RAFTConfig, name: str = "raft",
                     mixed: bool = False, loader_factory=None,
                     resume: bool = True, **overrides) -> None:
    """`train_standard.sh` / `train_mixed.sh` analog: chain stages, each
    restoring the previous stage's final weights with a fresh schedule
    (train_standard.sh:4-6). ``loader_factory(cfg)`` overrides the stage
    dataloader (tests / custom data).

    Restart semantics (``resume=True``, the default): a stage whose
    final ``.msgpack`` already exists AND passes its integrity manifest
    is SKIPPED — its weights still chain into the next stage (a corrupt
    final is quarantined and the stage retrained, see
    :func:`_final_intact`) — and the in-progress stage resumes from
    its newest intact full-state checkpoint. A relaunched multi-day
    curriculum (wedge, preemption, supervisor restart) repeats no
    completed work instead of retraining finished stages from scratch.
    ``resume=False`` forces the old every-stage-from-scratch behavior.
    """
    from raft_tpu.config import stage_config

    prev_final: Optional[str] = None
    for stage in stages:
        cfg = stage_config(stage, mixed=mixed, name=f"{name}-{stage}",
                           restore_ckpt=prev_final, **overrides)
        final = os.path.join(cfg.checkpoint_dir, f"{cfg.name}.msgpack")
        if resume and os.path.exists(final) and _final_intact(final):
            print(f"stage {stage}: final weights already at {final} — "
                  "skipping (restart of a partially-done curriculum)",
                  flush=True)
            prev_final = final
            continue
        t0 = time.perf_counter()
        train(model_cfg, cfg, resume=resume,
              loader=loader_factory(cfg) if loader_factory else None)
        print(f"stage {stage} done in {time.perf_counter() - t0:.0f}s",
              flush=True)
        prev_final = final
