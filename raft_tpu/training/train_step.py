"""jit-compiled SPMD train step with full train state.

Capability upgrade over the reference (SURVEY.md §5 checkpoint/resume): the
state carries params, batch stats, optimizer state, and step — the reference
saves model weights only (train.py:185-187) and silently restarts its LR
schedule on resume.

Parallelism: the step is a plain ``jax.jit`` over a ``Mesh`` — batch enters
sharded (data/spatial axes), params replicated; XLA SPMD inserts the
gradient ``psum`` and conv halo exchanges. No hand-written collectives.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from raft_tpu.config import RAFTConfig, TrainConfig
from raft_tpu.models import RAFT
from raft_tpu.training.loss import sequence_loss, sequence_loss_subpixel
from raft_tpu.training.optim import make_optimizer


class RAFTTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads, new_batch_stats=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            batch_stats=(new_batch_stats if new_batch_stats is not None
                         else self.batch_stats),
            opt_state=new_opt_state,
        )


def create_train_state(model_cfg: RAFTConfig, train_cfg: TrainConfig,
                       rng: jax.Array,
                       image_hw: Tuple[int, int] = (64, 64),
                       init_variables: Optional[Dict] = None
                       ) -> RAFTTrainState:
    model = RAFT(model_cfg)
    if init_variables is None:
        img = jnp.zeros((1, *image_hw, 3))
        init_variables = model.init(rng, img, img, iters=1)
    tx, _ = make_optimizer(train_cfg.lr, train_cfg.num_steps,
                           train_cfg.wdecay, train_cfg.epsilon,
                           train_cfg.clip)
    params = init_variables["params"]
    return RAFTTrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=init_variables.get("batch_stats", {}),
        opt_state=tx.init(params),
        tx=tx,
    )


def make_train_step(model_cfg: RAFTConfig, train_cfg: TrainConfig):
    """Build the jittable (state, batch, rng) -> (state, metrics) step.

    batch: dict with image1/image2 (B,H,W,3), flow (B,H,W,2), valid (B,H,W).
    image1/image2/valid may arrive uint8 (the loader's low-bandwidth wire
    format) or float32 — the step casts on device. ``rng`` is a BASE key,
    constant across the run: the step derives its per-step key as
    ``fold_in(rng, state.step)``, so callers pass the same key every step
    and a resumed run reproduces the stream. Gaussian image noise
    (train.py:167-170) is applied on-device when ``train_cfg.add_noise``.
    """
    model = RAFT(model_cfg)
    freeze_bn = train_cfg.stage != "chairs"  # train.py:147-148
    has_bn = (not model_cfg.small)
    mutable = ["batch_stats"] if (has_bn and not freeze_bn) else []
    # fused loss: predictions stay in the upsampler's subpixel domain and
    # the loss meets them there — the (T,B,8H,8W,2) stack (~560 MB fp32 at
    # chairs-b8) and its cotangent never materialize. Identical values
    # (pinned in tests/test_loss_optim.py); basic model only. Tri-state
    # config (None = auto): the small model silently takes the standard
    # loss under auto, and warns only on an EXPLICIT True it can't honor.
    fused = (train_cfg.fused_loss is not False) and not model_cfg.small
    if train_cfg.fused_loss is True and model_cfg.small:
        warnings.warn(
            "fused_loss requested with the small model, which has no "
            "fused path (its upsampling is a plain 8x interpolate, not "
            "the learned convex mask the fusion rides on) — falling "
            "back to the standard sequence loss", stacklevel=2)

    def train_step(state: RAFTTrainState, batch: Dict[str, jax.Array],
                   rng: jax.Array):
        # Per-step key derived INSIDE the jitted step from the base key and
        # the step counter. Two wins over a host-side split chain: (a) a
        # resumed run replays the exact key sequence from state.step without
        # replaying the chain; (b) no per-step host dispatch — on the
        # round-5 remote tunnel a host jax.random.split between steps cost
        # ~730 ms/step of lost pipelining (BENCH_NOTES.md round 5).
        rng = jax.random.fold_in(rng, state.step)
        # Wire-format cast: accept uint8 images/valid from the loader's
        # low-bandwidth wire (lossless — see data/loader._collate) as well
        # as float32; the cast is a no-op for float32 inputs.
        image1 = batch["image1"].astype(jnp.float32)
        image2 = batch["image2"].astype(jnp.float32)
        valid = batch["valid"].astype(jnp.float32)
        if train_cfg.add_noise:
            rng, k0, k1, k2 = jax.random.split(rng, 4)
            stdv = jax.random.uniform(k0, (), minval=0.0, maxval=5.0)
            image1 = jnp.clip(
                image1 + stdv * jax.random.normal(k1, image1.shape),
                0.0, 255.0)
            image2 = jnp.clip(
                image2 + stdv * jax.random.normal(k2, image2.shape),
                0.0, 255.0)

        def loss_fn(params):
            variables = {"params": params}
            if has_bn:
                variables["batch_stats"] = state.batch_stats
            kwargs = dict(
                rngs={"dropout": rng} if model_cfg.dropout > 0 else {})
            if mutable:  # flax returns a 2-tuple whenever mutable is passed
                kwargs["mutable"] = mutable
            out = model.apply(
                variables, image1, image2, iters=train_cfg.iters,
                train=True, freeze_bn=freeze_bn, raw_predictions=fused,
                **kwargs,
            )
            if mutable:
                preds, updated = out
                new_bs = updated["batch_stats"]
            else:
                preds, new_bs = out, state.batch_stats
            loss_impl = sequence_loss_subpixel if fused else sequence_loss
            loss, metrics = loss_impl(
                preds, batch["flow"], valid, train_cfg.gamma)
            return loss, (metrics, new_bs)

        (loss, (metrics, new_bs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads, new_bs)
        metrics = dict(metrics, loss=loss,
                       grad_norm=optax.global_norm(grads))
        return new_state, metrics

    return train_step
