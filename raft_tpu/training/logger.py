"""Training logger: running means -> stdout + TensorBoard.

Replicates the reference ``Logger`` (train.py:89-133): running means of the
step metrics printed every ``sum_freq`` steps together with the step count
and current LR, scalars written to TensorBoard under the same names
(epe/1px/3px/5px/loss), and validation dicts written at eval points — the
metric names stay identical so dashboards remain comparable (SURVEY.md §5).

TensorBoard is optional: when unavailable, scalars also land in a JSONL file
next to the event log so headless runs stay observable.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Optional


class Logger:
    def __init__(self, log_dir: str = "runs", sum_freq: int = 100,
                 lr_fn: Optional[Callable[[int], float]] = None):
        self.sum_freq = sum_freq
        self.lr_fn = lr_fn
        self.total_steps = 0
        self.running: Dict[str, float] = {}
        self._t0 = time.perf_counter()
        self._last_steps = 0
        os.makedirs(log_dir, exist_ok=True)
        self._jsonl = open(os.path.join(log_dir, "metrics.jsonl"), "a")
        try:
            from torch.utils.tensorboard import SummaryWriter
            self.writer = SummaryWriter(log_dir)
        except Exception:
            self.writer = None

    def _print_status(self, means: Dict[str, float]):
        lr = float(self.lr_fn(self.total_steps)) if self.lr_fn else 0.0
        dt = time.perf_counter() - self._t0
        ips = (self.total_steps - self._last_steps) / max(dt, 1e-9)
        self._t0, self._last_steps = time.perf_counter(), self.total_steps
        # training status, mirroring train.py:97-103's fixed-width line
        metrics_str = ("".join(
            f"{means[k]:10.4f}, " for k in sorted(means)))
        print(f"[{self.total_steps + 1:6d}, {lr:10.7f}] {metrics_str}"
              f"({ips:.2f} steps/s)", flush=True)

    def push(self, metrics: Dict[str, float]):
        self.total_steps += 1
        for k, v in metrics.items():
            self.running[k] = self.running.get(k, 0.0) + float(v)
        if self.total_steps % self.sum_freq == self.sum_freq - 1:
            means = {k: v / self.sum_freq for k, v in self.running.items()}
            self._print_status(means)
            self.write_dict(means)
            self.running = {}

    def start_at(self, step: int):
        """Align the step counter to a restored train state. Also pins the
        steps/s baseline: without this, the first window after a resume
        computes (restored_steps + window) / (one window's wall time) —
        an arbitrary, usually inflated rate."""
        self.total_steps = step
        self._last_steps = step
        self._t0 = time.perf_counter()

    def push_sums(self, sums: Dict[str, float], n: int):
        """Ingest ``n`` steps' worth of metric SUMS at once and flush a
        status line + record for the window.

        Exists for device-side accumulation: fetching per-step scalars
        costs one host<->device round trip per step, which on a remote
        TPU backend caps the whole training loop at ~1/RTT steps/s
        (measured: 0.72 steps/s against a ~3 steps/s device). The trainer
        sums metrics on device and fetches once per ``sum_freq`` window,
        flushing at the same ``total_steps % sum_freq == sum_freq - 1``
        boundaries as :meth:`push` so records/labels stay step-aligned
        with the reference logger (train.py:97-103).

        Intentional divergence from :meth:`push` (ADVICE r3): the mean
        divides by the ACTUAL sample count ``n``. ``push`` mirrors the
        reference bug-for-bug and divides the first window (which holds
        only ``sum_freq - 1`` samples) by ``sum_freq``, understating its
        means by ~1/sum_freq; this path reports the true mean instead.
        Every later window holds exactly ``sum_freq`` samples, where the
        two paths agree.
        """
        if n <= 0:
            return
        self.total_steps += n
        means = {k: float(v) / n for k, v in sums.items()}
        self._print_status(means)
        self.write_dict(means)

    def write_dict(self, results: Dict[str, float]):
        rec = {"step": self.total_steps}
        for k, v in results.items():
            rec[k] = float(v)
            if self.writer is not None:
                self.writer.add_scalar(k, float(v), self.total_steps)
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()

    def close(self):
        if self.writer is not None:
            self.writer.close()
        self._jsonl.close()
