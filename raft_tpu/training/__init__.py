from raft_tpu.training.loss import sequence_loss  # noqa: F401
from raft_tpu.training.optim import (  # noqa: F401
    make_optimizer,
    onecycle_linear_schedule,
)
from raft_tpu.training.train_step import (  # noqa: F401
    RAFTTrainState,
    create_train_state,
    make_train_step,
)
