"""Sequence loss over iterative flow predictions (train.py:47-72)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.config import MAX_FLOW
from raft_tpu.ops.flow_ops import standard_to_subpixel


def _gamma_weighted_masked_l1(flow_preds, gt, vmask, gamma):
    """sum_i gamma^(T-1-i) * mean(vmask * |pred_i - gt|) — the mean runs
    over ALL elements, not just valid ones, matching train.py:58-60."""
    T = flow_preds.shape[0]
    i = jnp.arange(T, dtype=jnp.float32)
    weights = gamma ** (T - 1 - i)                     # (T,)
    l1 = jnp.abs(flow_preds - gt[None])
    per_iter = (vmask * l1).mean(axis=tuple(range(1, l1.ndim)))
    return jnp.sum(weights * per_iter)


def _final_pred_metrics(epe, valid):
    """epe/1px/3px/5px over valid pixels of the final prediction
    (train.py:62-70). ``epe`` and ``valid`` share one shape."""
    vf = valid.astype(jnp.float32)
    count = jnp.maximum(vf.sum(), 1.0)

    def vmean(x):
        return (x * vf).sum() / count

    return {
        "epe": vmean(epe),
        "1px": vmean((epe < 1).astype(jnp.float32)),
        "3px": vmean((epe < 3).astype(jnp.float32)),
        "5px": vmean((epe < 5).astype(jnp.float32)),
    }


def sequence_loss(flow_preds: jax.Array, flow_gt: jax.Array,
                  valid: jax.Array, gamma: float = 0.8,
                  max_flow: float = MAX_FLOW
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """γ-weighted L1 over all iteration outputs.

    flow_preds: (T, B, H, W, 2) — scan-stacked predictions.
    flow_gt:    (B, H, W, 2); valid: (B, H, W).

    Pixels that are invalid or whose GT magnitude >= ``max_flow`` are
    excluded (train.py:53-55). The per-iteration weight is
    gamma**(T-1-i) (train.py:58), and — matching the reference exactly —
    the masked L1 is averaged over ALL elements, not just valid ones
    (``(valid[:, None] * i_loss).mean()``, train.py:60).
    """
    mag = jnp.sqrt(jnp.sum(flow_gt ** 2, axis=-1))
    valid = (valid >= 0.5) & (mag < max_flow)          # (B, H, W)
    vmask = valid[None, ..., None].astype(jnp.float32)  # (1, B, H, W, 1)

    flow_loss = _gamma_weighted_masked_l1(flow_preds, flow_gt, vmask, gamma)
    epe = jnp.sqrt(jnp.sum((flow_preds[-1] - flow_gt) ** 2, axis=-1))
    return flow_loss, _final_pred_metrics(epe, valid)


def sequence_loss_subpixel(up_raw: jax.Array, flow_gt: jax.Array,
                           valid: jax.Array, gamma: float = 0.8,
                           max_flow: float = MAX_FLOW
                           ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """:func:`sequence_loss` computed in the upsampler's subpixel domain.

    up_raw: (T, B, 2, 64, H*W) — ``convex_upsample_batched_raw`` output.
    flow_gt (B, 8H, 8W, 2), valid (B, 8H, 8W) are transformed ONCE into
    the same layout; every reduction is over full element sets (or
    valid-masked sums), so the values are identical to the standard
    path while the (T,B,8H,8W,2) prediction stack — ~560 MB fp32 at
    chairs-b8 — and its cotangent never materialize.
    """
    gt_t = standard_to_subpixel(flow_gt)               # (B, 2, 64, HW)
    valid_t = standard_to_subpixel(valid[..., None])[:, 0]  # (B, 64, HW)

    mag = jnp.sqrt(jnp.sum(gt_t ** 2, axis=1))         # (B, 64, HW)
    valid_t = (valid_t >= 0.5) & (mag < max_flow)
    vmask = valid_t[None, :, None].astype(jnp.float32)  # (1, B, 1, 64, HW)

    flow_loss = _gamma_weighted_masked_l1(up_raw, gt_t, vmask, gamma)
    epe = jnp.sqrt(jnp.sum((up_raw[-1] - gt_t) ** 2, axis=1))
    return flow_loss, _final_pred_metrics(epe, valid_t)
