"""Sequence loss over iterative flow predictions (train.py:47-72)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.config import MAX_FLOW


def sequence_loss(flow_preds: jax.Array, flow_gt: jax.Array,
                  valid: jax.Array, gamma: float = 0.8,
                  max_flow: float = MAX_FLOW
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """γ-weighted L1 over all iteration outputs.

    flow_preds: (T, B, H, W, 2) — scan-stacked predictions.
    flow_gt:    (B, H, W, 2); valid: (B, H, W).

    Pixels that are invalid or whose GT magnitude >= ``max_flow`` are
    excluded (train.py:53-55). The per-iteration weight is
    gamma**(T-1-i) (train.py:58), and — matching the reference exactly —
    the masked L1 is averaged over ALL elements, not just valid ones
    (``(valid[:, None] * i_loss).mean()``, train.py:60).
    """
    T = flow_preds.shape[0]
    mag = jnp.sqrt(jnp.sum(flow_gt ** 2, axis=-1))
    valid = (valid >= 0.5) & (mag < max_flow)          # (B, H, W)
    vmask = valid[None, ..., None].astype(jnp.float32)  # (1, B, H, W, 1)

    i = jnp.arange(T, dtype=jnp.float32)
    weights = gamma ** (T - 1 - i)                     # (T,)

    l1 = jnp.abs(flow_preds - flow_gt[None])           # (T, B, H, W, 2)
    per_iter = (vmask * l1).mean(axis=(1, 2, 3, 4))    # (T,)
    flow_loss = jnp.sum(weights * per_iter)

    # metrics on the final prediction, valid pixels only (train.py:62-70)
    epe = jnp.sqrt(jnp.sum((flow_preds[-1] - flow_gt) ** 2, axis=-1))
    vf = valid.astype(jnp.float32)
    count = jnp.maximum(vf.sum(), 1.0)

    def vmean(x):
        return (x * vf).sum() / count

    metrics = {
        "epe": vmean(epe),
        "1px": vmean((epe < 1).astype(jnp.float32)),
        "3px": vmean((epe < 3).astype(jnp.float32)),
        "5px": vmean((epe < 5).astype(jnp.float32)),
    }
    return flow_loss, metrics
