"""Orbax checkpointing of the FULL train state.

Capability upgrade over the reference, which saves model weights only every
5k steps (train.py:185-187) and silently restarts the LR schedule on resume
(SURVEY.md §5): here params, batch stats, optimizer state, and step are all
saved, so preempted TPU jobs resume exactly. Weights-only export/import is
kept for eval and for parity with the reference's ``.pth`` lifecycle
(``raft_tpu.tools.convert`` handles the torch side).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

from raft_tpu.training.train_step import RAFTTrainState


# one long-lived manager per directory: Orbax saves stay genuinely async
# (creating + closing a manager per save would block on wait_until_finished)
_MANAGERS: dict = {}


def _manager(ckpt_dir: str, max_to_keep: int = 20) -> ocp.CheckpointManager:
    path = os.path.abspath(ckpt_dir)
    mgr = _MANAGERS.get(path)
    if mgr is None:
        os.makedirs(path, exist_ok=True)
        mgr = ocp.CheckpointManager(
            path, options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))
        _MANAGERS[path] = mgr
    return mgr


def close_all() -> None:
    """Flush and close every open manager (call at end of training)."""
    for mgr in _MANAGERS.values():
        mgr.wait_until_finished()
        mgr.close()
    _MANAGERS.clear()


def _as_tree(state: RAFTTrainState) -> Dict[str, Any]:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


def save_train_state(ckpt_dir: str, state: RAFTTrainState,
                     step: Optional[int] = None, wait: bool = False) -> None:
    """Async save (Orbax) of the full state at ``step``."""
    mgr = _manager(ckpt_dir)
    step = int(state.step) if step is None else int(step)
    mgr.save(step, args=ocp.args.StandardSave(_as_tree(state)))
    if wait:
        mgr.wait_until_finished()


def latest_step(ckpt_dir: str) -> Optional[int]:
    path = os.path.abspath(ckpt_dir)
    if not os.path.isdir(path):
        return None
    return _manager(path).latest_step()


def restore_train_state(ckpt_dir: str, state: RAFTTrainState,
                        step: Optional[int] = None) -> RAFTTrainState:
    """Restore into the (freshly created) ``state`` template; ``tx`` is
    rebuilt by the caller's ``create_train_state`` and kept as-is."""
    mgr = _manager(ckpt_dir)
    mgr.wait_until_finished()  # a just-issued async save must be visible
    step = mgr.latest_step() if step is None else step
    if step is None:
        raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, _as_tree(state))
    tree = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    return state.replace(
        step=tree["step"], params=tree["params"],
        batch_stats=tree["batch_stats"], opt_state=tree["opt_state"])


def save_weights(path: str, variables: Dict[str, Any]) -> None:
    """Weights-only save (msgpack), the ``torch.save(state_dict)`` analog."""
    from raft_tpu.tools.convert import save_converted

    save_converted(variables, path)


def variables_from_state(state: RAFTTrainState) -> Dict[str, Any]:
    out = {"params": state.params}
    if state.batch_stats:
        out["batch_stats"] = state.batch_stats
    return out
