"""Orbax checkpointing of the FULL train state.

Capability upgrade over the reference, which saves model weights only every
5k steps (train.py:185-187) and silently restarts the LR schedule on resume
(SURVEY.md §5): here params, batch stats, optimizer state, and step are all
saved, so preempted TPU jobs resume exactly. Weights-only export/import is
kept for eval and for parity with the reference's ``.pth`` lifecycle
(``raft_tpu.tools.convert`` handles the torch side).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp
from flax import serialization

from raft_tpu.testing import faults
from raft_tpu.tools.convert import manifest_path, verify_manifest
from raft_tpu.training.restore_sandbox import STEP_UNREADABLE_EXIT
from raft_tpu.training.train_step import RAFTTrainState
from raft_tpu.utils.ckpt_scan import (preflight_step, quarantine_path,
                                      step_dirs)


class StepDamagedError(RuntimeError):
    """The restore sandbox judged a specific step unreadable (torn,
    corrupt, or a crash while reading it) — the ONLY failure class the
    fallback path may quarantine. Everything else a restore can raise
    (disk full writing the snapshot, a broken sandbox env, a template
    mismatch) is not evidence against the step, and quarantining on it
    would shred an intact checkpoint history over a transient error."""


#: sandbox deaths of the poisoned-read crash class — the native-reader
#: failure modes a torn/corrupt step provokes (SEGV/ABRT/BUS/ILL/FPE)
#: and therefore evidence AGAINST the step. Deliberately excludes
#: SIGKILL/SIGTERM: the OOM killer and process managers signal the
#: sandbox for reasons that say nothing about the step's bytes, and on
#: a memory-tight host an OOM-SIGKILL per attempt would otherwise
#: cascade-quarantine the entire intact history.
_CRASH_SIGNALS = frozenset(int(s) for s in (
    signal.SIGSEGV, signal.SIGABRT, signal.SIGBUS, signal.SIGILL,
    signal.SIGFPE))

#: wall-clock budget for one sandbox restore (seconds; env-overridable,
#: 0 disables). The sandbox runs BEFORE the trainer's HangWatch is
#: armed, so without a deadline a tensorstore read that BLOCKS on
#: damaged input (rather than erroring or crashing) would wedge resume
#: forever with no watchdog to kill it — under a supervisor, eternally.
_SANDBOX_TIMEOUT_ENV = "RAFT_RESTORE_TIMEOUT_S"
_SANDBOX_TIMEOUT_DEFAULT_S = 900.0

# one long-lived manager per directory: Orbax saves stay genuinely async
# (creating + closing a manager per save would block on wait_until_finished)
_MANAGERS: dict = {}


def _manager(ckpt_dir: str, max_to_keep: int = 20) -> ocp.CheckpointManager:
    path = os.path.abspath(ckpt_dir)
    mgr = _MANAGERS.get(path)
    if mgr is None:
        os.makedirs(path, exist_ok=True)
        mgr = ocp.CheckpointManager(
            path, options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))
        _MANAGERS[path] = mgr
    return mgr


def close_all() -> None:
    """Flush and close every open manager (call at end of training)."""
    for mgr in _MANAGERS.values():
        mgr.wait_until_finished()
        mgr.close()
    _MANAGERS.clear()


def _as_tree(state: RAFTTrainState) -> Dict[str, Any]:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


def save_train_state(ckpt_dir: str, state: RAFTTrainState,
                     step: Optional[int] = None, wait: bool = False) -> None:
    """Async save (Orbax) of the full state at ``step``."""
    mgr = _manager(ckpt_dir)
    step = int(state.step) if step is None else int(step)
    # snapshot to an OWNED host copy before backgrounding the write:
    # the training step donates its state buffers (train_step
    # donate_argnums), and on the CPU backend orbax's "copy to host"
    # phase aliases the live buffer instead of copying — a backgrounded
    # serialize then races XLA's donation reuse (observed under the
    # fault drills: checkpoints with torn step values, glibc heap
    # corruption aborts minutes later). On TPU this device_get is the
    # same D2H transfer orbax performs synchronously anyway.
    tree = jax.device_get(_as_tree(state))
    mgr.save(step, args=ocp.args.StandardSave(tree))
    if faults.armed("ckpt.orbax_save"):
        # corruption drills smash the step's on-disk files, which
        # requires the async save to have finished materializing them;
        # the wait runs only while a drill is live
        mgr.wait_until_finished()
        path = os.path.abspath(ckpt_dir)
        for s, name in step_dirs(path):
            if s == step:
                victim = faults.fault_file("ckpt.orbax_save",
                                           os.path.join(path, name))
                if victim:
                    print(f"[faults] corrupted {victim}", flush=True)
                break
        faults.fault_point("ckpt.orbax_save")
    if wait:
        mgr.wait_until_finished()


def latest_step(ckpt_dir: str) -> Optional[int]:
    path = os.path.abspath(ckpt_dir)
    if not os.path.isdir(path):
        return None
    return _manager(path).latest_step()


def _quarantine_step(ckpt_dir: str, step: int) -> Optional[str]:
    """Rename a torn/corrupt step dir aside (``<dir>.corrupt``) so no
    future ``latest_step`` can ever hand it out again; returns the new
    path. Renames, never deletes — the bytes stay around for forensics."""
    path = os.path.abspath(ckpt_dir)
    # drop the open manager first: it holds a cached view of (and async
    # machinery over) the directory being renamed under it
    mgr = _MANAGERS.pop(path, None)
    if mgr is not None:
        try:
            mgr.wait_until_finished()
            mgr.close()
        except Exception:
            pass  # a broken manager must not block the fallback path
    for s, name in step_dirs(path):
        if s != step:
            continue
        src = os.path.join(path, name)
        dst = quarantine_path(src)
        os.rename(src, dst)
        return dst
    return None


def restore_train_state(ckpt_dir: str, state: RAFTTrainState,
                        step: Optional[int] = None) -> RAFTTrainState:
    """Restore into the (freshly created) ``state`` template; ``tx`` is
    rebuilt by the caller's ``create_train_state`` and kept as-is.

    With ``step=None`` (the resume path) this restores the newest
    *intact* step: a torn or corrupt latest — crash mid-save, bit rot,
    an injected drill — is quarantined aside with a logged warning and
    the next-newest step is tried, so auto-resume recovers instead of
    wedging on (or silently loading) a bad checkpoint. An explicit
    ``step`` fails loudly: the caller named it, so substituting another
    would be lying.
    """
    path = os.path.abspath(ckpt_dir)
    mgr = _MANAGERS.get(path)
    if mgr is not None:
        mgr.wait_until_finished()  # a just-issued async save must be visible

    def _restore(dir_name: str) -> RAFTTrainState:
        # the orbax read runs in a throwaway subprocess that
        # re-serializes the step as an atomic, SHA-256-manifested
        # msgpack snapshot (restore_sandbox has the full story: a
        # tensorstore read of a torn/corrupt step poisons the reader's
        # heap even when it errors cleanly, so the read happens where
        # death is cheap and detection is an exit code). This trainer
        # process only ever parses the verified snapshot; its heap
        # never meets tensorstore's reader.
        snap = os.path.join(path, f"restore-snapshot.tmp.{os.getpid()}"
                                  ".msgpack")
        env = dict(os.environ)
        # drills target the trainer's own write/read sites, not the
        # sandbox's re-serialization
        env.pop("RAFT_FAULT_PLAN", None)
        env.pop("RAFT_FAULT_PLAN_FILE", None)
        timeout_s = float(os.environ.get(_SANDBOX_TIMEOUT_ENV,
                                         _SANDBOX_TIMEOUT_DEFAULT_S))
        try:
            try:
                proc = subprocess.run(
                    [sys.executable, "-m",
                     "raft_tpu.training.restore_sandbox",
                     os.path.join(path, dir_name), snap],
                    env=env, capture_output=True, text=True,
                    timeout=timeout_s or None)
            except subprocess.TimeoutExpired as exc:
                # run() has killed the sandbox. A read that blocks past
                # a generous deadline is the third face of the damaged-
                # step class (alongside clean errors and native
                # crashes): the sandbox is CPU-only by construction, so
                # a wedged backend can't explain it. A systemic IO hang
                # (dead NFS) would burn timeout_s per step and
                # quarantine loudly down the history — slow, printed,
                # and reversible (renames, never deletes) — which beats
                # the alternative: resume wedged forever with no
                # watchdog armed yet, a supervisor waiting on a child
                # that never exits.
                raise StepDamagedError(
                    f"restore sandbox for step dir {dir_name!r} hung "
                    f"past {timeout_s:.0f}s ({_SANDBOX_TIMEOUT_ENV}) "
                    "and was killed — treating the step as unreadable"
                ) from exc
            if proc.returncode != 0:
                msg = (f"restore sandbox failed for step dir "
                       f"{dir_name!r} (exit {proc.returncode}): "
                       f"{proc.stderr.strip()[-500:]}")
                # a step-unreadable verdict or a sandbox death by a
                # crash-class signal (the poisoned-read failure modes)
                # indicts the step; any other failure — ENV_ERROR_EXIT,
                # usage, import trouble, an OOM/operator SIGKILL or
                # SIGTERM — indicts the environment and must not feed
                # the quarantine path
                if (proc.returncode == STEP_UNREADABLE_EXIT
                        or -proc.returncode in _CRASH_SIGNALS):
                    raise StepDamagedError(msg)
                raise RuntimeError(msg)
            with open(snap, "rb") as fh:
                data = fh.read()
            verify_manifest(snap, data)
            tree = serialization.from_bytes(_as_tree(state), data)
            # launder every leaf through an on-device copy so ONLY
            # XLA-owned buffers reach the donated train step: on this
            # jaxlib, device_put of host numpy arrays can zero-copy
            # alias python-owned memory, and donating such a buffer
            # lets XLA reuse/free memory the allocator doesn't own —
            # latent heap corruption that aborts the recovered run at
            # an allocation-layout-dependent point (the fault drills
            # reproduced this; fresh XLA-created states never crash).
            tree = jax.tree.map(lambda x: jnp.array(x, copy=True), tree)
        finally:
            for p in (snap, manifest_path(snap)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        return state.replace(
            step=tree["step"], params=tree["params"],
            batch_stats=tree["batch_stats"], opt_state=tree["opt_state"])

    if step is not None:
        return _restore(str(step))

    skipped = []
    while True:
        dirs = step_dirs(path)
        if not dirs:
            raise FileNotFoundError(
                f"no intact checkpoint under {ckpt_dir}" + (
                    f" (quarantined corrupt step(s): {skipped})"
                    if skipped else ""))
        s, name = dirs[0]
        # pure-python integrity probe BEFORE orbax opens the step: a
        # torn/corrupt step fed to the restore machinery can poison
        # the process heap even when it raises a clean python error.
        # A step must prove its metadata parses before any native
        # reader touches it; see ckpt_scan.preflight_step.
        reason = preflight_step(os.path.join(path, name))
        restored = None
        if reason is None:
            try:
                restored = _restore(name)
            except StepDamagedError as exc:
                # damage past the metadata probe (data-file payloads):
                # same quarantine-and-fall-back, via the sandbox's
                # step-unreadable verdict. Deliberately NOT a broad
                # except: a systemic failure (disk full, broken env)
                # raising here for every step would otherwise
                # quarantine the entire intact history and silently
                # restart training from scratch
                reason = f"{type(exc).__name__}: {exc}"
        if reason is not None:
            dst = _quarantine_step(path, s)
            skipped.append(s)
            print(f"checkpoint step {s} under {ckpt_dir} is torn/corrupt "
                  f"({reason}); quarantined to "
                  f"{dst or '<step dir not found>'} — falling back to "
                  "the next newest", flush=True)
            continue
        if skipped:
            print(f"resumed from fallback step {s} (skipped corrupt "
                  f"step(s) {skipped})", flush=True)
        return restored


def save_weights(path: str, variables: Dict[str, Any]) -> None:
    """Weights-only save (msgpack), the ``torch.save(state_dict)`` analog."""
    from raft_tpu.tools.convert import save_converted

    save_converted(variables, path)


def variables_from_state(state: RAFTTrainState) -> Dict[str, Any]:
    out = {"params": state.params}
    if state.batch_stats:
        out["batch_stats"] = state.batch_stats
    return out
