"""Input padding to stride-8 alignment (NHWC).

Equivalent of ``core/utils/utils.py:7-24`` (class form) and
``raft_trt_utils.py:8-21`` (functional form). Padding is replicate-edge;
'sintel' centers the pad, 'kitti' pads only the bottom (``utils.py:16`` —
F.pad's height pair is (top=0, bottom=pad_ht)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pad_amounts(ht: int, wd: int, mode: str = "sintel"):
    pad_ht = (((ht // 8) + 1) * 8 - ht) % 8
    pad_wd = (((wd // 8) + 1) * 8 - wd) % 8
    if mode == "sintel":
        # (left, right, top, bottom)
        return (pad_wd // 2, pad_wd - pad_wd // 2, pad_ht // 2, pad_ht - pad_ht // 2)
    return (pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht)


class InputPadder:
    """Pads NHWC images so H and W are divisible by 8."""

    def __init__(self, dims, mode: str = "sintel"):
        # dims: a shape tuple (..., H, W, C) — NHWC.
        self.ht, self.wd = dims[-3], dims[-2]
        self._pad = pad_amounts(self.ht, self.wd, mode)

    def pad(self, *inputs):
        l, r, t, b = self._pad
        out = [jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)), mode="edge")
               for x in inputs]
        return out if len(out) > 1 else out[0]

    def unpad(self, x: jax.Array) -> jax.Array:
        l, r, t, b = self._pad
        ht, wd = x.shape[-3], x.shape[-2]
        return x[..., t:ht - b, l:wd - r, :]


def pad_to_multiple(images: jax.Array, mode: str = "sintel"):
    """Functional pad (``raft_trt_utils.py:8-14`` analog). Returns (padded, pad)."""
    padder = InputPadder(images.shape, mode)
    return padder.pad(images), padder._pad


def unpad(x: jax.Array, pad) -> jax.Array:
    l, r, t, b = pad
    ht, wd = x.shape[-3], x.shape[-2]
    return x[..., t:ht - b, l:wd - r, :]
