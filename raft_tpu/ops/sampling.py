"""Bilinear sampling primitives (NHWC, TPU-first).

The reference wraps ``torch.nn.functional.grid_sample`` with pixel-coordinate
inputs (``/root/reference/core/utils/utils.py:57-71``, align_corners=True,
zero padding).  TPUs have no grid_sample primitive and pointwise gathers are
the weak spot, so this implements sampling as *flattened-index gathers* with
manual corner weights — a form XLA lowers to efficient dynamic-gathers — and
keeps everything channels-last so the channel dim rides the 128-wide lane
dimension of the VPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def coords_grid(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jax.Array:
    """Pixel-coordinate grid, shape (batch, ht, wd, 2), last dim (x, y).

    Equivalent of ``core/utils/utils.py:74-77`` (which returns (B,2,H,W) with
    channel 0 = x); here channels-last.
    """
    xs = jnp.arange(wd, dtype=dtype)
    ys = jnp.arange(ht, dtype=dtype)
    x, y = jnp.meshgrid(xs, ys, indexing="xy")
    grid = jnp.stack([x, y], axis=-1)
    return jnp.broadcast_to(grid[None], (batch, ht, wd, 2))


def grid_sample_nhwc(img: jax.Array, x: jax.Array, y: jax.Array) -> jax.Array:
    """Bilinear sample ``img`` (B, H, W, C) at pixel coords ``x``/``y`` (B, ...).

    Matches ``F.grid_sample(mode='bilinear', padding_mode='zeros',
    align_corners=True)`` fed pixel coordinates: corners that land outside the
    image contribute zero but the in-bounds corners keep their bilinear
    weights. Returns (B, ..., C).
    """
    B, H, W, C = img.shape
    pos_shape = x.shape  # (B, ...)
    x = x.reshape(B, -1).astype(jnp.float32)
    y = y.reshape(B, -1).astype(jnp.float32)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    flat = img.reshape(B, H * W, C)

    def corner(xi, yi, w):
        valid = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xi_c = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        idx = yi_c * W + xi_c  # (B, N)
        vals = jnp.take_along_axis(flat, idx[..., None], axis=1)  # (B, N, C)
        w = (w * valid.astype(jnp.float32))[..., None]
        return vals * w.astype(vals.dtype)

    out = (
        corner(x0, y0, (1.0 - wx) * (1.0 - wy))
        + corner(x0 + 1.0, y0, wx * (1.0 - wy))
        + corner(x0, y0 + 1.0, (1.0 - wx) * wy)
        + corner(x0 + 1.0, y0 + 1.0, wx * wy)
    )
    return out.reshape(*pos_shape, C)


def bilinear_sampler(img: jax.Array, coords: jax.Array) -> jax.Array:
    """Sample ``img`` (B, H, W, C) at ``coords`` (B, ..., 2), last dim (x, y).

    NHWC analog of ``core/utils/utils.py:57-71``.
    """
    return grid_sample_nhwc(img, coords[..., 0], coords[..., 1])
