"""Flow-field ops: initialization, upsampling, convex upsampling (NHWC).

Equivalents of ``core/raft.py:63-83`` and ``core/utils/utils.py:80-82``, laid
out channels-last and expressed as einsums so XLA can fuse/tile them for the
MXU/VPU instead of the unfold+view dance the reference does.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.ops.sampling import coords_grid, grid_sample_nhwc


def initialize_flow(batch: int, ht: int, wd: int):
    """coords0, coords1 at 1/8 resolution; flow = coords1 - coords0.

    Analog of ``core/raft.py:63-70`` (inputs already divided by 8 here).
    """
    coords0 = coords_grid(batch, ht, wd)
    coords1 = coords_grid(batch, ht, wd)
    return coords0, coords1


def resize_bilinear_align_corners(x: jax.Array, out_hw) -> jax.Array:
    """Bilinear resize with align_corners=True semantics, NHWC.

    ``jax.image.resize`` uses half-pixel centers, which does NOT match
    ``F.interpolate(..., align_corners=True)`` (``core/utils/utils.py:82``);
    align_corners maps output i -> input i*(H_in-1)/(H_out-1), so we sample
    explicitly.
    """
    B, H, W, C = x.shape
    oh, ow = out_hw
    sy = (H - 1) / (oh - 1) if oh > 1 else 0.0
    sx = (W - 1) / (ow - 1) if ow > 1 else 0.0
    ys = jnp.arange(oh, dtype=jnp.float32) * sy
    xs = jnp.arange(ow, dtype=jnp.float32) * sx
    gx, gy = jnp.meshgrid(xs, ys, indexing="xy")
    gx = jnp.broadcast_to(gx[None], (B, oh, ow))
    gy = jnp.broadcast_to(gy[None], (B, oh, ow))
    return grid_sample_nhwc(x, gx, gy)


def upflow8(flow: jax.Array) -> jax.Array:
    """8x bilinear upsample of a (B, H, W, 2) flow field, scaling values by 8.

    Analog of ``core/utils/utils.py:80-82``; used by the small model, which
    has no learned upsampling mask (``core/raft.py:134-135``).
    """
    B, H, W, _ = flow.shape
    return 8.0 * resize_bilinear_align_corners(flow, (8 * H, 8 * W))


def convex_upsample(flow: jax.Array, mask: jax.Array) -> jax.Array:
    """Learned convex-combination 8x upsample. flow (B,H,W,2), mask (B,H,W,576).

    Analog of ``core/raft.py:72-83``. The 576 mask channels factor as
    (9 neighbors, 8 sub-rows, 8 sub-cols) in C-order — i.e. channel
    c = k*64 + i*8 + j — matching ``mask.view(N, 1, 9, 8, 8, H, W)``; the 9
    neighbors enumerate the 3x3 window row-major ((dy,dx) = (-1,-1)..(1,1)),
    matching ``F.unfold(8*flow, [3,3], padding=1)``. Output pixel
    (8h+i, 8w+j) = sum_k softmax(mask)[k,i,j] * 8*flow[h+dy_k, w+dx_k].
    """
    # One-frame view of the lane-tiled batched form (identical math: the
    # (9,64) factoring of the 576 channels is the (9,8,8) factoring with
    # (i,j) flattened, and the softmax runs over the same 9 axis in fp32 —
    # the convex combination stays an fp32 island as the reference computes
    # it outside autocast). The previous per-frame stacked-neighborhood
    # einsum hit the same TPU pathology measured for the batched path
    # (see the measurement note in convex_upsample_batched_raw): tiny
    # k=9 contraction, large layout copies.
    B, H, W, _ = flow.shape
    return subpixel_to_standard(
        convex_upsample_batched_raw(flow[None], mask[None]), H, W)[0]


def convex_upsample_batched(flow: jax.Array, mask: jax.Array) -> jax.Array:
    """Convex 8x upsample of a STACK of iterations: standard layout out.

    flow (T, B, H, W, 2) fp32, mask (T, B, H, W, 576) -> (T, B, 8H, 8W, 2).
    """
    T, B, H, W, _ = flow.shape
    return subpixel_to_standard(
        convex_upsample_batched_raw(flow, mask), H, W)


def convex_upsample_batched_raw(flow: jax.Array,
                                mask: jax.Array) -> jax.Array:
    """Convex 8x upsample of a STACK of iterations at once, tiled for TPU.

    flow (T, B, H, W, 2) fp32, mask (T, B, H, W, 576) any float dtype ->
    (T, B, 2, 64, H*W) fp32 in the SUBPIXEL domain (s = 8i+j on dim 3,
    n = W*h+w on dim 4); :func:`subpixel_to_standard` finishes the layout.
    The raw form exists so the fused sequence loss can consume the stack
    without ever materializing the (T,B,8H,8W,2) tensor (~560 MB fp32 at
    chairs-b8) or its cotangent. Same math as :func:`convex_upsample` per
    frame (softmax and combination in fp32), but laid out
    pixels-on-lanes.

    Why this exists (measured, XProf r3 session C): inside the refinement
    scan the per-iteration formulation materializes (B,H,W,9,8,8) tensors
    whose minor (8,8) dims occupy 64 slots of the TPU's (8,128) memory
    tile — ~16x physical padding — so the upsample fwd+bwd plus its layout
    copies burned ~35% of the 500 ms train step at 30-70 GB/s effective.
    Here every large intermediate keeps minor dims (64-multiple, H*W):
    near-perfect (8,128) tiling. B/H/W stay separate axes (merged only as
    H*W, major-sharded-H-compatible) so data x spatial mesh shardings
    propagate without gathers.
    """
    T, B, H, W, _ = flow.shape
    HW = H * W
    # (T,B,H,W,576) -> (T,B,HW,9,64) -> (T,B,9,64,HW); softmax over the 9
    # neighbors AFTER the transpose so the reduction runs lanes-minor
    m = mask.astype(jnp.float32).reshape(T, B, HW, 9, 64)
    m = m.transpose(0, 1, 3, 4, 2)
    w9 = jax.nn.softmax(m, axis=2)

    # Convex combination as 9 shifted multiply-accumulates instead of a
    # stacked-neighborhood einsum: the k=9 "GEMM" contraction is tiny, so
    # dot_general buys no MXU win but forces the (T,B,2,9,HW) neighbor
    # stack plus layout copies of the 630 MB weight tensor around it.
    # Measured on chip (round 5, isolated fwd+bwd at chairs-b8 geometry):
    # einsum form 1176 ms, this form 28 ms; identical values (the k-sum
    # runs in fp32 either way).
    fp = jnp.pad(8.0 * flow.astype(jnp.float32),
                 ((0, 0), (0, 0), (1, 1), (1, 1), (0, 0)))
    up = jnp.zeros((T, B, 2, 64, HW), jnp.float32)
    for k, (dy, dx) in enumerate((dy, dx) for dy in range(3)
                                 for dx in range(3)):
        sh = fp[:, :, dy:dy + H, dx:dx + W, :]            # (T,B,H,W,2)
        sh = sh.transpose(0, 1, 4, 2, 3).reshape(T, B, 2, 1, HW)
        up = up + w9[:, :, k][:, :, None] * sh
    return up  # (T, B, 2, 64, H*W); subpixel s = 8i + j, n = W*h + w


def subpixel_to_standard(up: jax.Array, H: int, W: int) -> jax.Array:
    """(T, B, 2, 64, H*W) subpixel-domain stack -> (T, B, 8H, 8W, 2)."""
    T, B = up.shape[:2]
    up = up.reshape(T, B, 2, 8, 8, H, W)
    up = up.transpose(0, 1, 5, 3, 6, 4, 2)      # (t,b,h,i,w,j,c)
    return up.reshape(T, B, 8 * H, 8 * W, 2)


def standard_to_subpixel(x: jax.Array) -> jax.Array:
    """(B, 8H, 8W, C) -> (B, C, 64, H*W): the inverse image-side transform
    of :func:`subpixel_to_standard`, for targets/masks that must meet the
    upsampler's raw output in its own lane-tiled domain (fused loss). A
    trailing scalar field can be passed as (B, 8H, 8W, 1)."""
    B, H8, W8, C = x.shape
    H, W = H8 // 8, W8 // 8
    x = x.reshape(B, H, 8, W, 8, C)             # (b,h,i,w,j,c)
    x = x.transpose(0, 5, 2, 4, 1, 3)           # (b,c,i,j,h,w)
    return x.reshape(B, C, 64, H * W)


def upflow8_batched(flow: jax.Array) -> jax.Array:
    """:func:`upflow8` over a (T, B, H, W, 2) iteration stack at once."""
    T, B, H, W, _ = flow.shape
    out = upflow8(flow.reshape(T * B, H, W, 2))
    return out.reshape(T, B, 8 * H, 8 * W, 2)
