"""Pooling helpers (NHWC)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def avg_pool2x2(x: jax.Array) -> jax.Array:
    """2x2 stride-2 average pool over the two middle dims of (..., H, W, C).

    Matches ``F.avg_pool2d(x, 2, stride=2)`` (floor division of odd sizes —
    trailing row/col dropped), used for the correlation pyramid
    (``core/corr.py:25-27``).
    """
    ones = (1,) * (x.ndim - 3)
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=ones + (2, 2, 1),
        window_strides=ones + (2, 2, 1),
        padding="VALID",
    )
    return summed * 0.25
