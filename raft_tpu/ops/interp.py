"""Host-side forward interpolation for warm-start flow.

Equivalent of ``core/utils/utils.py:26-54``: forward-warp the previous
frame's low-res flow via nearest-neighbor scattered interpolation. This is a
deliberate host round-trip in the reference too (scipy griddata on CPU); it
runs once per frame in the Sintel submission writer, off the hot path.
"""

from __future__ import annotations

import numpy as np
from scipy import interpolate


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """flow: (H, W, 2) numpy array (x, y channels last). Returns same shape."""
    flow = np.asarray(flow)
    dx, dy = flow[..., 0], flow[..., 1]

    ht, wd = dx.shape
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))

    x1 = (x0 + dx).reshape(-1)
    y1 = (y0 + dy).reshape(-1)
    dx = dx.reshape(-1)
    dy = dy.reshape(-1)

    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    x1, y1, dx, dy = x1[valid], y1[valid], dx[valid], dy[valid]

    flow_x = interpolate.griddata((x1, y1), dx, (x0, y0),
                                  method="nearest", fill_value=0)
    flow_y = interpolate.griddata((x1, y1), dy, (x0, y0),
                                  method="nearest", fill_value=0)
    return np.stack([flow_x, flow_y], axis=-1).astype(np.float32)
