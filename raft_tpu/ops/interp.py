"""Host-side forward interpolation for warm-start flow.

Equivalent of ``core/utils/utils.py:26-54``: forward-warp the previous
frame's low-res flow via nearest-neighbor scattered interpolation. This is a
deliberate host round-trip in the reference too (scipy griddata on CPU); it
runs once per frame in the Sintel submission writer, off the hot path.
"""

from __future__ import annotations

import numpy as np
from scipy import interpolate


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """flow: (H, W, 2) numpy array (x, y channels last). Returns same shape."""
    flow = np.asarray(flow)
    dx, dy = flow[..., 0], flow[..., 1]

    ht, wd = dx.shape
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))

    x1 = (x0 + dx).reshape(-1)
    y1 = (y0 + dy).reshape(-1)
    dx = dx.reshape(-1)
    dy = dy.reshape(-1)

    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    x1, y1, dx, dy = x1[valid], y1[valid], dx[valid], dy[valid]

    flow_x = interpolate.griddata((x1, y1), dx, (x0, y0),
                                  method="nearest", fill_value=0)
    flow_y = interpolate.griddata((x1, y1), dy, (x0, y0),
                                  method="nearest", fill_value=0)
    return np.stack([flow_x, flow_y], axis=-1).astype(np.float32)


_FWD_JIT = None


def forward_interpolate_device(flow):
    """On-device forward warp for device-resident session state
    (``VideoSession(device_state=True)``): scatter each source pixel's
    flow to its rounded target cell, dropping points that leave the
    frame (the same validity window as the host path).

    Deliberately CHEAPER than the scipy version, not equivalent: cells
    no warped point lands in stay ZERO (a locally cold start — always a
    valid refinement init) instead of being nearest-neighbor filled by
    ``griddata``'s global query, which has no reasonable on-device
    form. Non-finite flow rows fail every validity comparison and
    scatter nothing, so a poisoned previous pair degrades to a full
    cold start on device — the NaN guard the host path does with
    ``np.isfinite`` — without ever forcing a D2H sync. Duplicate
    targets resolve arbitrarily-but-deterministically (XLA scatter),
    exactly like ``griddata``'s nearest-of-ties.

    ``flow``: (H, W, 2) jax array; returns the same shape/dtype, still
    on device. Jitted once; each distinct shape compiles a tiny
    scatter program."""
    import jax
    import jax.numpy as jnp

    global _FWD_JIT
    if _FWD_JIT is None:
        def _fwd(flow):
            ht, wd = flow.shape[0], flow.shape[1]
            y0, x0 = jnp.meshgrid(jnp.arange(ht), jnp.arange(wd),
                                  indexing="ij")
            x1 = x0 + flow[..., 0]
            y1 = y0 + flow[..., 1]
            valid = ((x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht))
            xi = jnp.clip(jnp.round(x1).astype(jnp.int32), 0, wd - 1)
            yi = jnp.clip(jnp.round(y1).astype(jnp.int32), 0, ht - 1)
            # invalid points target a drop slot past the grid
            idx = jnp.where(valid, yi * wd + xi, ht * wd)
            out = jnp.zeros((ht * wd + 1, 2), flow.dtype)
            out = out.at[idx.reshape(-1)].set(
                flow.reshape(-1, 2), mode="drop")
            return out[:ht * wd].reshape(ht, wd, 2)
        _FWD_JIT = jax.jit(_fwd)
    return _FWD_JIT(flow)
