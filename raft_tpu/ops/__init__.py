from raft_tpu.ops.sampling import (  # noqa: F401
    bilinear_sampler,
    coords_grid,
    grid_sample_nhwc,
)
from raft_tpu.ops.flow_ops import (  # noqa: F401
    convex_upsample,
    initialize_flow,
    upflow8,
    resize_bilinear_align_corners,
)
from raft_tpu.ops.padding import InputPadder, pad_to_multiple, unpad  # noqa: F401
from raft_tpu.ops.pooling import avg_pool2x2  # noqa: F401
